"""ImageFeaturizer — transfer-learning featurization from zoo models.

Analog of the reference's ``src/image-featurizer/`` (reference:
ImageFeaturizer.scala:116-140): resize the image to the model's input
dims, normalize, run the truncated network, emit the activation vector.
``cut_output_layers`` counts named output nodes dropped from the end —
0 keeps the head (logits), 1 yields the penultimate features, matching
the reference's ``setCutOutputLayers`` over the zoo schema's
``layerNames``.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import (
    ArrayMeta, DeviceOp, DeviceStage, HasInputCol, HasOutputCol, Transformer,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.stages.image import ImageTransformer


class ImageFeaturizer(Transformer, DeviceStage, HasInputCol, HasOutputCol):
    """Transfer learning from zoo models: resize to the model's input size,
    unroll, and run a truncated forward pass (``cut_output_layers`` picks the
    intermediate node per the bundle's ``layer_names``). Reference:
    image-featurizer/src/main/scala/ImageFeaturizer.scala:116-140."""

    input_col = Param(default="image", doc="input image column", type_=str)
    output_col = Param(default="features", doc="output feature column",
                       type_=str)
    model = Param(default=None, doc="ModelBundle to featurize with",
                  is_complex=True)
    cut_output_layers = Param(
        default=1, doc="number of output nodes cut from the end "
        "(0 = keep the full head)", type_=int, validator=Param.ge(0))
    minibatch_size = Param(default=None, doc="device minibatch size",
                           type_=int)

    def set_model_by_name(self, name: str, **kwargs: Any) -> "ImageFeaturizer":
        from mmlspark_tpu.models.zoo import get_model
        self.set(model=get_model(name, **kwargs))
        return self

    def set_model_from_repo(self, name: str, repo: Any = None,
                            cache_dir: str | None = None
                            ) -> "ImageFeaturizer":
        """Fetch a *pretrained* bundle through ``ModelDownloader`` (manifest
        + sha256 cache) — the reference's zoo-download → featurize flow
        (ModelDownloader.scala:224-251 → ImageFeaturizer.scala:70-74)."""
        from mmlspark_tpu.data.downloader import (
            ModelDownloader, load_bundle_file,
        )
        path = ModelDownloader(repo, cache_dir).download_by_name(name)
        self.set(model=load_bundle_file(path))
        return self

    def _resolve_cut_node(self, bundle: ModelBundle) -> str:
        cut = self.cut_output_layers
        names = bundle.output_names
        if cut >= len(names):
            raise ValueError(
                f"cut_output_layers={cut} but model has only "
                f"{len(names)} output nodes {names}")
        return names[len(names) - 1 - cut]

    def _stages(self) -> list:
        """The resize→forward stage pair, built once per configuration so
        the planner's compiled-segment cache (keyed by stage identity)
        stays warm across transform calls."""
        bundle: ModelBundle = self.model
        if bundle is None:
            raise ValueError("ImageFeaturizer: no model set")
        h, w = bundle.input_spec[0], bundle.input_spec[1]
        key = (id(bundle), h, w, self._resolve_cut_node(bundle),
               self.minibatch_size, self.input_col, self.output_col)
        cached = self.__dict__.get("_stage_cache")
        if cached is not None and cached[0] == key:
            return cached[1]
        rt = ImageTransformer(
            input_col=self.input_col, output_col=self.input_col,
        ).resize(h, w)
        jm = JaxModel(
            input_col=self.input_col,
            output_col=self.output_col,
            output_node=self._resolve_cut_node(bundle),
            minibatch_size=self.minibatch_size,
        )
        jm.set(model=bundle)
        self.__dict__["_stage_cache"] = (key, [rt, jm])
        return [rt, jm]

    def __getstate__(self):
        d = self.__dict__.copy()
        for k in ("_stage_cache", "_plan_cache", "_plan_lock"):
            d.pop(k, None)
        return d

    def transform(self, table: DataTable) -> DataTable:
        # resize + truncated forward go through the pipeline planner: on
        # device-friendly tables they fuse into ONE compiled program (single
        # H2D upload of the raw uint8 batch per minibatch — ~h*w/32²× fewer
        # bytes than shipping resized f32 — and one async fetch); anything
        # the planner declines runs the same two stages on host, unchanged
        from mmlspark_tpu.core import plan
        return plan.execute_stages(self._stages(), table, cache_host=self)

    # ---- static schema inference: compose the internal resize→forward
    #      stages' own inference, so the predicted features layout is the
    #      traced truth (eval_shape through the truncated node) and the
    #      materialized resized image column is modeled too ----

    def infer_schema(self, schema: Any) -> Any:
        from mmlspark_tpu.analysis.info import (
            SchemaError, require_image_input,
        )
        if self.model is None:
            raise SchemaError(
                "model-not-set",
                "ImageFeaturizer has no model bundle; set model=, "
                "set_model_by_name(), or set_model_from_repo() first")
        require_image_input(schema, self.input_col, "ImageFeaturizer")
        for stage in self._stages():
            schema = stage.infer_schema(schema)
        return schema

    # ---- DeviceStage protocol: resize∘forward as one composable op, so
    #      an ImageFeaturizer inside a larger pipeline fuses with its
    #      neighbors. Declines when the resize would actually change the
    #      image dims: transform() also *materializes* the resized image
    #      column, and a fused op that skipped that would diverge from the
    #      stage-by-stage result. ----

    def device_cache_token(self):
        bundle = self.model
        return (None if bundle is None else
                (id(bundle.module), id(bundle.params), bundle.preprocess),
                self.input_col, self.output_col,
                self.cut_output_layers, self.minibatch_size)

    def device_fingerprint(self):
        """Stable content identity for the persistent AOT compile cache
        (the weights-digest counterpart of ``device_cache_token``)."""
        bundle = self.model
        if bundle is None:
            return None
        from mmlspark_tpu.core.compile_cache import bundle_digest
        return ("ImageFeaturizer", bundle_digest(bundle),
                self.input_col, self.output_col,
                self.cut_output_layers, self.minibatch_size)

    def device_fn(self, meta: ArrayMeta) -> DeviceOp | None:
        bundle: ModelBundle = self.model
        if bundle is None or not meta.is_image or len(meta.shape) != 3:
            return None
        h, w = bundle.input_spec[0], bundle.input_spec[1]
        if tuple(meta.shape[:2]) != (h, w):
            return None  # transform() would rewrite the image column
        rt, jm = self._stages()
        resize_op = rt.device_fn(meta)
        if resize_op is None:
            return None
        fwd_op = jm.device_fn(resize_op.out_meta)
        if fwd_op is None:
            return None

        def fn(params, x):
            return fwd_op.fn(params, resize_op.fn((), x))

        return DeviceOp(fn, fwd_op.out_meta, params=fwd_op.params)

"""Model layer: the JaxModel inference transformer, model bundles, and the
built-in architecture zoo.

Analog of the reference's DNN backend ``src/cntk-model/`` +
``src/image-featurizer/`` + ``src/downloader/`` model zoo, rebuilt on
JAX/flax: models are flax modules + pytree params instead of serialized
CNTK graphs reached over JNI.
"""

from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.repo import (
    ModelRepo, ModelRepoError, ModelVersion, RepoCorruptError,
    VersionNotFound,
)

__all__ = ["ModelBundle", "JaxModel", "ModelRepo", "ModelRepoError",
           "ModelVersion", "RepoCorruptError", "VersionNotFound"]

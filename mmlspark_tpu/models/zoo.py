"""Built-in model architectures (the model-zoo analog).

The reference's zoo is a manifest of pretrained CNTK graphs (ConvNet
CIFAR-10, ResNet-50, …) downloaded by ``ModelDownloader`` (reference:
downloader/src/main/scala/{ModelDownloader,Schema}.scala). Here
architectures are flax modules defined in-repo; weights come either from
random init (training) or downloaded checkpoints
(:mod:`mmlspark_tpu.data.downloader`).

TPU-first choices: NHWC layout (XLA:TPU's native conv layout), bfloat16
compute with float32 params/accumulation, channel counts in MXU-friendly
multiples of 128 where the architecture allows, named output nodes for
featurization cuts (the ``cutOutputLayers`` analog, reference:
image-featurizer/src/main/scala/ImageFeaturizer.scala:116-140).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mmlspark_tpu.models.bundle import ModelBundle


class PatchConv3x3(nn.Module):
    """3×3 same-padding stride-1 conv on a tiny-channel input, computed in
    2×2 space-to-depth form — numerically identical, MXU-shaped.

    A direct RGB-stem conv contracts over just 3 of the MXU's 128 lanes —
    measured ~1.7 TFLOP/s on v5e, ~40× off peak, dominating the whole CIFAR
    step (PERF_NOTES.md). Reorganizing 2×2 pixel blocks into channels makes
    the same op a [B·H/2·W/2, 9·4·cin] × [9·4·cin, 4·features] matmul
    (contraction 108 wide, output 256 wide for the CIFAR stem): 4× fewer
    output tiles, 4× the contraction depth. The block-form weight matrix is
    assembled at trace time from the standard ``nn.Conv`` parameter layout
    ((3,3,cin,features) kernel + bias), so checkpoints are interchangeable
    with the direct formulation; zero entries encode the taps that fall
    outside each output pixel's 3×3 window.

    Requires even H and W (pad the input otherwise).
    """

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        cin, F = x.shape[-1], self.features
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (3, 3, cin, F))
        bias = self.param("bias", nn.initializers.zeros, (F,))
        B, H, W = x.shape[0], x.shape[1], x.shape[2]
        if H % 2 or W % 2:
            raise ValueError(f"PatchConv3x3 needs even H/W, got {H}x{W}")
        k = kernel.astype(self.dtype)
        # block-form weights Wb[(rb·3+cb)·4cin + (uu·2+vv)·cin + c,
        #                       (u·2+v)·F + f]
        #   = kernel[dy, dx, c, f] at dy = 2rb+uu-u-1, dx = 2cb+vv-v-1
        # (zero where the tap leaves the 3×3 window)
        wb = jnp.zeros((9 * 4 * cin, 4 * F), self.dtype)
        for rb in range(3):
            for cb in range(3):
                for uu in range(2):
                    for vv in range(2):
                        p0 = ((rb * 3 + cb) * 4 + uu * 2 + vv) * cin
                        for u in range(2):
                            dy = 2 * rb + uu - u - 1
                            if not 0 <= dy < 3:
                                continue
                            for v in range(2):
                                dx = 2 * cb + vv - v - 1
                                if not 0 <= dx < 3:
                                    continue
                                q0 = (u * 2 + v) * F
                                wb = wb.at[p0:p0 + cin, q0:q0 + F].set(
                                    k[dy, dx])
        h, w = H // 2, W // 2
        # space-to-depth: [B,H,W,cin] -> [B,h,w,4cin], block channel
        # (uu·2+vv)·cin + c
        xs = x.astype(self.dtype).reshape(B, h, 2, w, 2, cin)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(B, h, w, 4 * cin)
        # one zero block of padding: the conv's SAME halo lives in the
        # nearest row/col of each neighbor block, the rest hits zeros in wb
        xp = jnp.pad(xs, ((0, 0), (1, 1), (1, 1), (0, 0)))
        patches = jnp.concatenate(
            [xp[:, i:i + h, j:j + w, :] for i in range(3) for j in range(3)],
            axis=-1)
        y = patches @ wb  # [B,h,w,4F]
        # depth-to-space back to [B,H,W,F]
        y = y.reshape(B, h, w, 2, 2, F).transpose(0, 1, 3, 2, 4, 5)
        y = y.reshape(B, H, W, F)
        return y + bias.astype(self.dtype)


class ConvNetCifar(nn.Module):
    """CIFAR-10 ConvNet — flagship model, notebook-301 analog.

    Mirrors the capability of the reference zoo's ``ConvNet_CIFAR10`` entry
    (conv/pool stack + dense head). Compute runs in bfloat16 for the MXU;
    params stay float32. The RGB stem runs as :class:`PatchConv3x3` (same
    parameters, MXU-friendly formulation).

    Output nodes (selectable like CNTK node names): ``features`` (penultimate
    dense activations, used by ImageFeaturizer) and ``logits``.
    """

    num_classes: int = 10
    # MXU-sized widths: measured step MFU on v5e is 54.9% at (64,128,256)
    # but 76.7% at (128,256,512) — the narrow stem/blocks leave MXU lanes
    # idle, wide ones fill them (PERF_NOTES.md round-2 table)
    widths: Sequence[int] = (128, 256, 512)
    dense_width: int = 512
    dtype: Any = jnp.bfloat16
    stem: str = "direct"  # "direct" (nn.Conv) | "patch" (s2d matmul form);
    # measured in the full train step XLA's direct lowering beats the
    # hand-rolled s2d form (8.4 vs 9.8 ms/step @ B=1024) — keep "direct"

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False):
        x = x.astype(self.dtype)
        for i, w in enumerate(self.widths):
            if x.shape[-1] < 32 and self.stem == "patch":
                x = PatchConv3x3(w, dtype=self.dtype, name=f"conv{i}a")(x)
            else:
                x = nn.Conv(w, (3, 3), dtype=self.dtype, name=f"conv{i}a")(x)
            x = nn.relu(x)
            x = nn.Conv(w, (3, 3), dtype=self.dtype, name=f"conv{i}b")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.dense_width, dtype=self.dtype, name="dense0")(x)
        x = nn.relu(x)
        features = x.astype(jnp.float32)
        if output == "features":
            return features
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)


class MLP(nn.Module):
    """Plain MLP — used by TrainClassifier's NN family and tests."""

    features: Sequence[int] = (128, 128)
    num_outputs: int = 2
    dtype: Any = jnp.float32

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense{i}")(x)
            x = nn.relu(x)
        if output == "features":
            return x.astype(jnp.float32)
        return nn.Dense(self.num_outputs, name="head")(x).astype(jnp.float32)


# ---- zoo registry ----

ZOO: dict[str, Callable[..., ModelBundle]] = {}


def register_model(name: str):
    def deco(fn):
        ZOO[name] = fn
        return fn
    return deco


def init_bundle(module: Any, input_spec: tuple, name: str,
                preprocess: str | None = None, seed: int = 0,
                output_names: tuple | None = None) -> ModelBundle:
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1,) + tuple(input_spec), jnp.float32)
    variables = module.init(rng, dummy)
    return ModelBundle(
        module=module,
        params=variables["params"],
        input_spec=tuple(input_spec),
        output_names=output_names or getattr(
            type(module), "OUTPUT_NAMES", ("logits",)),
        preprocess=preprocess,
        name=name,
    )


@register_model("ConvNet_CIFAR10")
def conv_net_cifar(num_classes: int = 10, seed: int = 0, **kw) -> ModelBundle:
    return init_bundle(ConvNetCifar(num_classes=num_classes, **kw),
                       (32, 32, 3), "ConvNet_CIFAR10",
                       preprocess="center_128", seed=seed)


@register_model("MLP")
def mlp(input_dim: int = 16, num_outputs: int = 2, seed: int = 0,
        **kw) -> ModelBundle:
    return init_bundle(MLP(num_outputs=num_outputs, **kw),
                       (input_dim,), "MLP", seed=seed)


@register_model("ResNet50")
def resnet50_bundle(num_classes: int = 1000, input_size: int = 224,
                    seed: int = 0, **kw) -> ModelBundle:
    """BASELINE config 3 backbone (reference zoo's pretrained ResNet-50,
    Schema.scala:54-74). GroupNorm variant — see models/resnet.py."""
    from mmlspark_tpu.models.resnet import resnet50
    return init_bundle(resnet50(num_classes=num_classes, **kw),
                       (input_size, input_size, 3), "ResNet50",
                       preprocess="imagenet_norm", seed=seed)


def _folded_resnet_bundle(name: str, factory: Any, num_classes: int,
                          input_size: int, seed: int,
                          param_dtype: Any, **kw) -> ModelBundle:
    """Init a frozen-BN net and fold its statistics into the conv weights
    (models/resnet.py:fold_batchnorm). The published zoo path folds
    *trained* statistics at publish time (tools/build_model_repo.py); this
    zoo entry folds the init stats so the inference architecture is
    constructible without a repo download."""
    from mmlspark_tpu.models.resnet import fold_batchnorm
    bn_net = factory(num_classes=num_classes, norm="batch", **kw)
    dummy = jnp.zeros((1, input_size, input_size, 3), jnp.float32)
    # init + fold are host-side setup (the fold itself is numpy): pin them
    # to the CPU backend so bundle construction never pays a remote-device
    # compile/transfer for a 224² init it immediately folds away. A
    # JAX_PLATFORMS pin that excludes cpu makes the backend unavailable —
    # fall back to the default device there
    import contextlib
    try:
        ctx = jax.default_device(jax.devices("cpu")[0])
    except RuntimeError:
        ctx = contextlib.nullcontext()
    with ctx:
        variables = bn_net.init(jax.random.PRNGKey(seed), dummy)
        params = fold_batchnorm(variables, param_dtype=param_dtype)
    folded = factory(num_classes=num_classes, norm="none", **kw)
    return ModelBundle(module=folded, params=params,
                       input_spec=(input_size, input_size, 3),
                       output_names=type(folded).OUTPUT_NAMES,
                       preprocess="imagenet_norm", name=name)


@register_model("ResNet50_Infer")
def resnet50_infer_bundle(num_classes: int = 1000, input_size: int = 224,
                          seed: int = 0, param_dtype: Any = jnp.bfloat16,
                          stem: str = "s2d", **kw) -> ModelBundle:
    """Frozen-norm inference ResNet-50 — the featurization variant.

    The reference's zoo ResNet-50 is a BatchNorm network whose frozen
    inference statistics fold into the conv weights (Schema.scala:54-74,
    ImageFeaturizer.scala:116-140) — zero norm cost at scoring time. This
    is the TPU-native equivalent: ``norm="none"`` architecture + folded
    params (bf16 by default — frozen inference weights need no f32
    master) + the space-to-depth stem (``stem="s2d"``, same param layout).
    Measured on v5e at batch 256/224²: 0.39 MFU (GroupNorm train variant)
    → 0.64 MFU folded (PERF_NOTES round 5)."""
    from mmlspark_tpu.models.resnet import resnet50
    return _folded_resnet_bundle("ResNet50_Infer", resnet50, num_classes,
                                 input_size, seed, param_dtype, stem=stem,
                                 **kw)


@register_model("ResNet_Small_Infer")
def resnet_small_infer_bundle(num_classes: int = 10, input_size: int = 32,
                              seed: int = 0,
                              param_dtype: Any = jnp.bfloat16,
                              stem: str = "s2d", **kw) -> ModelBundle:
    """CI-scale folded variant (same fold path as ResNet50_Infer)."""
    from mmlspark_tpu.models.resnet import resnet18_thin
    return _folded_resnet_bundle("ResNet_Small_Infer", resnet18_thin,
                                 num_classes, input_size, seed,
                                 param_dtype, stem=stem, **kw)


@register_model("ResNet_Small")
def resnet_small_bundle(num_classes: int = 10, input_size: int = 32,
                        seed: int = 0, **kw) -> ModelBundle:
    """Same ResNet family at CI scale (tests, local-repo publishing)."""
    from mmlspark_tpu.models.resnet import resnet18_thin
    return init_bundle(resnet18_thin(num_classes=num_classes, **kw),
                       (input_size, input_size, 3), "ResNet_Small",
                       preprocess="imagenet_norm", seed=seed)


@register_model("ViT_B16")
def vit_b16_bundle(num_classes: int = 1000, input_size: int = 224,
                   seed: int = 0, **kw) -> ModelBundle:
    """BASELINE config 5 flagship (distributed fine-tune)."""
    from mmlspark_tpu.models.vit import vit_b16
    return init_bundle(vit_b16(num_classes=num_classes, **kw),
                       (input_size, input_size, 3), "ViT_B16",
                       preprocess="scale_pm1", seed=seed)


@register_model("ViT_Tiny")
def vit_tiny_bundle(num_classes: int = 10, input_size: int = 32,
                    seed: int = 0, **kw) -> ModelBundle:
    from mmlspark_tpu.models.vit import vit_tiny
    return init_bundle(vit_tiny(num_classes=num_classes, **kw),
                       (input_size, input_size, 3), "ViT_Tiny",
                       preprocess="scale_pm1", seed=seed)


@register_model("BiLSTM_MedTag")
def bilstm_medtag_bundle(vocab_size: int = 8192, num_tags: int = 16,
                         max_len: int = 613, seed: int = 0,
                         **kw) -> ModelBundle:
    """Notebook-304 analog (medical entity tagger; the reference pads
    sentences to a fixed 613 tokens — kept as the default input length)."""
    import jax as _jax

    from mmlspark_tpu.models.sequence import BiLSTMTagger
    module = BiLSTMTagger(vocab_size=vocab_size, num_tags=num_tags, **kw)
    tokens = jnp.zeros((1, max_len), jnp.int32)
    params = module.init(_jax.random.PRNGKey(seed), tokens)["params"]
    return ModelBundle(module=module, params=params, input_spec=(max_len,),
                       output_names=BiLSTMTagger.OUTPUT_NAMES,
                       name="BiLSTM_MedTag")


def get_model(name: str, **kwargs: Any) -> ModelBundle:
    if name not in ZOO:
        raise KeyError(f"unknown zoo model {name!r}; available: {sorted(ZOO)}")
    return ZOO[name](**kwargs)

"""Built-in model architectures (the model-zoo analog).

The reference's zoo is a manifest of pretrained CNTK graphs (ConvNet
CIFAR-10, ResNet-50, …) downloaded by ``ModelDownloader`` (reference:
downloader/src/main/scala/{ModelDownloader,Schema}.scala). Here
architectures are flax modules defined in-repo; weights come either from
random init (training) or downloaded checkpoints
(:mod:`mmlspark_tpu.data.downloader`).

TPU-first choices: NHWC layout (XLA:TPU's native conv layout), bfloat16
compute with float32 params/accumulation, channel counts in MXU-friendly
multiples of 128 where the architecture allows, named output nodes for
featurization cuts (the ``cutOutputLayers`` analog, reference:
image-featurizer/src/main/scala/ImageFeaturizer.scala:116-140).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mmlspark_tpu.models.bundle import ModelBundle


class ConvNetCifar(nn.Module):
    """CIFAR-10 ConvNet — flagship model, notebook-301 analog.

    Mirrors the capability of the reference zoo's ``ConvNet_CIFAR10`` entry
    (conv/pool stack + dense head). Compute runs in bfloat16 for the MXU;
    params stay float32.

    Output nodes (selectable like CNTK node names): ``features`` (penultimate
    dense activations, used by ImageFeaturizer) and ``logits``.
    """

    num_classes: int = 10
    widths: Sequence[int] = (64, 128, 256)
    dense_width: int = 512
    dtype: Any = jnp.bfloat16

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False):
        x = x.astype(self.dtype)
        for i, w in enumerate(self.widths):
            x = nn.Conv(w, (3, 3), dtype=self.dtype, name=f"conv{i}a")(x)
            x = nn.relu(x)
            x = nn.Conv(w, (3, 3), dtype=self.dtype, name=f"conv{i}b")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.dense_width, dtype=self.dtype, name="dense0")(x)
        x = nn.relu(x)
        features = x.astype(jnp.float32)
        if output == "features":
            return features
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)


class MLP(nn.Module):
    """Plain MLP — used by TrainClassifier's NN family and tests."""

    features: Sequence[int] = (128, 128)
    num_outputs: int = 2
    dtype: Any = jnp.float32

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense{i}")(x)
            x = nn.relu(x)
        if output == "features":
            return x.astype(jnp.float32)
        return nn.Dense(self.num_outputs, name="head")(x).astype(jnp.float32)


# ---- zoo registry ----

ZOO: dict[str, Callable[..., ModelBundle]] = {}


def register_model(name: str):
    def deco(fn):
        ZOO[name] = fn
        return fn
    return deco


def init_bundle(module: Any, input_spec: tuple, name: str,
                preprocess: str | None = None, seed: int = 0,
                output_names: tuple | None = None) -> ModelBundle:
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1,) + tuple(input_spec), jnp.float32)
    variables = module.init(rng, dummy)
    return ModelBundle(
        module=module,
        params=variables["params"],
        input_spec=tuple(input_spec),
        output_names=output_names or getattr(
            type(module), "OUTPUT_NAMES", ("logits",)),
        preprocess=preprocess,
        name=name,
    )


@register_model("ConvNet_CIFAR10")
def conv_net_cifar(num_classes: int = 10, seed: int = 0, **kw) -> ModelBundle:
    return init_bundle(ConvNetCifar(num_classes=num_classes, **kw),
                       (32, 32, 3), "ConvNet_CIFAR10",
                       preprocess="center_128", seed=seed)


@register_model("MLP")
def mlp(input_dim: int = 16, num_outputs: int = 2, seed: int = 0,
        **kw) -> ModelBundle:
    return init_bundle(MLP(num_outputs=num_outputs, **kw),
                       (input_dim,), "MLP", seed=seed)


def get_model(name: str, **kwargs: Any) -> ModelBundle:
    if name not in ZOO:
        raise KeyError(f"unknown zoo model {name!r}; available: {sorted(ZOO)}")
    return ZOO[name](**kwargs)

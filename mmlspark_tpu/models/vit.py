"""ViT-B/16 — distributed fine-tune flagship (BASELINE config 5).

A vision transformer is the natural TPU model: patch embedding and every
block are large dense matmuls that map straight onto the MXU, and the whole
forward is static-shaped. Design:

* 16×16 patch embed as a strided conv (one big matmul per image),
* pre-LN encoder blocks (MHSA + MLP), bfloat16 compute / float32 params,
* global-average-pool head (the standard GAP variant — no class token, so
  featurization and sequence handling stay uniform with the other models),
* ``features`` node = pooled, final-LN embedding (the featurizer cut),
  ``logits`` = classification head.

The B/16 configuration (12 layers, 768 wide, 12 heads, 3072 MLP) matches
the ubiquitous checkpoint family; smaller configs are constructor args so
tests exercise the same class.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class BhtdSelfAttention(nn.Module):
    """Self-attention computed in ``[B, H, T, dh]`` layout.

    Parameter tree is identical to flax's
    ``nn.MultiHeadDotProductAttention`` (``query``/``key``/``value``
    DenseGeneral kernels ``[D, H, dh]`` and ``out`` kernel ``[H, dh, D]``),
    so checkpoints are interchangeable — only the compute layout differs:
    the head axis moves next to batch BEFORE the score/weighted-sum
    einsums instead of XLA inserting transposes around each one
    (measured ~4% faster fwd+bwd at ViT-B shapes on v5e, PERF_NOTES
    round 4).

    ``impl`` selects the attention compute (same params either way):

    * ``"einsum"`` — the historical two-einsum + full softmax path;
    * ``"flash"`` / ``"flash_xla"`` / ``"flash_pallas"`` — the fused
      online-softmax path (:mod:`mmlspark_tpu.ops.pallas.attention`,
      the serving-path attention: the score matrix never materializes
      in HBM), mapping to the kernel's ``auto``/``xla``/``pallas``
      backend selection.
    """

    heads: int
    dtype: Any = jnp.bfloat16
    impl: str = "einsum"

    IMPLS = ("einsum", "flash", "flash_xla", "flash_pallas")

    @nn.compact
    def __call__(self, x):
        if self.impl not in self.IMPLS:
            # validate up front: 'pallas'/'xla' (the kernel's own flag
            # vocabulary) must not silently run the einsum path
            raise ValueError(
                f"unknown attention impl {self.impl!r}; one of "
                f"{list(self.IMPLS)}")
        B, T, D = x.shape
        H = self.heads
        dh = D // H
        q = nn.DenseGeneral((H, dh), dtype=self.dtype, name="query")(x)
        k = nn.DenseGeneral((H, dh), dtype=self.dtype, name="key")(x)
        v = nn.DenseGeneral((H, dh), dtype=self.dtype, name="value")(x)
        k = k.transpose(0, 2, 1, 3)                  # [B,H,T,dh]
        v = v.transpose(0, 2, 1, 3)
        if self.impl.startswith("flash"):
            from mmlspark_tpu.ops.pallas.attention import flash_attention
            kernel_impl = {"flash": "auto", "flash_xla": "xla",
                           "flash_pallas": "pallas"}[self.impl]
            o = flash_attention(q.transpose(0, 2, 1, 3), k, v,
                                impl=kernel_impl)
            o = o.astype(self.dtype)
        else:
            q = q.transpose(0, 2, 1, 3) * (dh ** -0.5)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
            probs = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        o = o.transpose(0, 2, 1, 3)                  # [B,T,H,dh]
        return nn.DenseGeneral(D, axis=(-2, -1), dtype=self.dtype,
                               name="out")(o)


class EncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    attn_impl: str = "bhtd"   # "bhtd" | "flax" (same params either way)

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        if self.attn_impl == "bhtd" or self.attn_impl.startswith("flash"):
            h = BhtdSelfAttention(
                heads=self.heads, dtype=self.dtype, name="attn",
                impl=("einsum" if self.attn_impl == "bhtd"
                      else self.attn_impl))(h)
        elif self.attn_impl == "flax":
            h = nn.MultiHeadDotProductAttention(
                num_heads=self.heads, dtype=self.dtype, name="attn")(h, h)
        else:
            # 'pallas'/'xla' (the kernel flag vocabulary) must not fall
            # through to the flax reference — its param tree differs, so
            # a checkpoint would fail to restore much later and opaquely
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; one of ['bhtd', "
                "'flax', 'flash', 'flash_xla', 'flash_pallas']")
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_out")(h)
        return x + h


class ViT(nn.Module):
    """Vision transformer with GAP head; defaults are B/16."""

    num_classes: int = 1000
    patch: int = 16
    dim: int = 768
    depth: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    # rematerialize each encoder block on the backward pass: activation HBM
    # drops from O(depth) block outputs to O(1), buying larger fine-tune
    # batches at ~1/3 extra forward FLOPs (jax.checkpoint semantics).
    # Measured on v5e it LOSES throughput at every batch that fits
    # (B=128: 137→178 ms/step) — memory capacity is not the binding
    # constraint there; the flag exists for models/batches that OOM
    remat: bool = False
    attn_impl: str = "bhtd"  # see BhtdSelfAttention; "flax" = reference;
    #                          "flash"/"flash_xla"/"flash_pallas" = the
    #                          fused online-softmax serving path
    #                          (ops/pallas/attention.py)
    # microbatch count when the encoder stack runs pipelined over a pp
    # mesh (bubble fraction (pp-1)/(M+pp-1)); batch must divide by
    # microbatches × dp extent
    pipeline_microbatches: int = 4

    OUTPUT_NAMES = ("features", "logits")

    def mesh_hooks(self, mesh) -> dict:
        """Trainer integration (train/loop.py:resolve_mesh_hooks): on a
        ``pp > 1`` mesh the encoder blocks run as the GPipe collective
        pipeline (parallel/pipeline.py) — same per-block params (and
        checkpoints) as the sequential stack."""
        kwargs: dict = {}
        handled: set = set()
        if mesh.shape.get("pp", 1) > 1:
            if self.depth % mesh.shape["pp"]:
                raise ValueError(
                    f"ViT depth {self.depth} not divisible by "
                    f"pp={mesh.shape['pp']}")
            kwargs["pipeline_mesh"] = mesh
            handled.add("pp")
        return {"apply_kwargs": kwargs, "param_rules": None,
                "handled": handled}

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False,
                 pipeline_mesh: Any = None):
        B, H, W, _ = x.shape
        if H % self.patch or W % self.patch:
            raise ValueError(
                f"input {H}x{W} not divisible by patch {self.patch}")
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), dtype=self.dtype,
                    name="patch_embed")(x.astype(self.dtype))
        h, w = x.shape[1], x.shape[2]
        x = x.reshape(B, h * w, self.dim)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (h * w, self.dim))
        x = x + pos[None].astype(self.dtype)
        if pipeline_mesh is not None and not self.is_initializing():
            x = self._pipelined_blocks(x, pipeline_mesh)
        else:
            block_cls = (nn.remat(EncoderBlock) if self.remat
                         else EncoderBlock)
            for i in range(self.depth):
                x = block_cls(self.dim, self.heads, self.mlp_dim,
                              dtype=self.dtype, attn_impl=self.attn_impl,
                              name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        x = jnp.mean(x, axis=1)  # GAP over patches
        features = x.astype(jnp.float32)
        if output == "features":
            return features
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(x)
        return logits.astype(jnp.float32)

    def _pipelined_blocks(self, x, mesh):
        """Run the encoder stack through the GPipe collective pipeline.

        Params keep the sequential layout (``block{i}`` subtrees — so
        checkpoints are interchangeable between pipelined and sequential
        runs, and a pp resume of a dp run just works); they are stacked
        on a leading layer axis at trace time and handed to
        :func:`~mmlspark_tpu.parallel.pipeline.pipeline_apply`, which
        pins the traced stack replicated
        (:func:`~mmlspark_tpu.parallel.pipeline.commit_replicated` — the
        GSPMD full-to-shard edge fed unpinned trace-built operands to
        each shard multiplied by the dp extent) and reshards it over
        ``pp`` inside its shard_map. The re-stack costs one device-local
        copy of the block params per step — the price of a single param
        layout across all execution paths. Gradients flow
        through the stack back to the per-block leaves (exact; the
        pipeline is collective-differentiable)."""
        from mmlspark_tpu.parallel.pipeline import (
            pipeline_apply, stack_layer_params,
        )

        template = EncoderBlock(self.dim, self.heads, self.mlp_dim,
                                dtype=self.dtype, attn_impl=self.attn_impl)
        params = self.variables["params"]
        stacked = stack_layer_params(
            [params[f"block{i}"] for i in range(self.depth)])

        def block_fn(p, h):
            return template.apply({"params": p}, h)

        if self.remat:  # honor the flag on this path too (jax.checkpoint
            # around each block application inside the pipeline scan)
            block_fn = jax.checkpoint(block_fn)

        return pipeline_apply(block_fn, stacked, x, mesh,
                              num_microbatches=self.pipeline_microbatches)


def vit_b16(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
            **kw: Any) -> ViT:
    return ViT(num_classes=num_classes, dtype=dtype, **kw)


def vit_tiny(num_classes: int = 10, image_patch: int = 8,
             dtype: Any = jnp.float32, **kw: Any) -> ViT:
    """Small same-class config for tests/CI."""
    return ViT(num_classes=num_classes, patch=image_patch, dim=64, depth=2,
               heads=4, mlp_dim=128, dtype=dtype, **kw)

"""ModelBundle — a self-contained, persistable (module, params) pair.

The reference ships DNN models as serialized CNTK graph bytes, broadcast to
executors and cloned per task (reference: cntk-model/src/main/scala/
SerializableFunction.scala:58-82, CNTKModel.scala:90-114). The TPU-native
equivalent is a flax module (architecture, stateless) plus a pytree of
weights; "cloning with shared weights" is free because JAX params are
immutable and jit-compiled functions are pure.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np


@dataclasses.dataclass
class ModelBundle:
    """A runnable model: flax module + params + IO contract.

    ``output_names`` enumerates selectable output nodes in graph order —
    the analog of CNTK output-node selection by name or index
    (reference: cntk-model/src/main/scala/CNTKModel.scala:98-108). Zoo
    modules accept ``output=<name>`` in ``__call__`` and return that node's
    activations; XLA dead-code-eliminates the rest of the graph above it.
    """

    module: Any                      # flax linen module (picklable dataclass)
    params: Any                      # pytree of weights
    input_spec: tuple                # per-example input shape, e.g. (32, 32, 3)
    output_names: tuple = ("logits",)
    preprocess: str | None = None    # named preprocessing ("scale_01", ...)
    name: str = "model"

    def resolve_output(self, node: str | int | None) -> str:
        """Resolve an output-node selector (name, index, or None=last)."""
        if node is None:
            return self.output_names[-1]
        if isinstance(node, int):
            if not 0 <= node < len(self.output_names):
                raise ValueError(
                    f"output node index {node} out of range; model has "
                    f"{len(self.output_names)} outputs: {self.output_names}")
            return self.output_names[node]
        if node not in self.output_names:
            raise ValueError(
                f"unknown output node {node!r}; available: {self.output_names}")
        return node

    def apply(self, x: Any, output: str | None = None) -> Any:
        """Full forward incl. the bundle's preprocessing — same math as the
        JaxModel pipeline path."""
        out = self.resolve_output(output)
        if self.preprocess:
            x = PREPROCESSORS[self.preprocess](x)
        return self.module.apply({"params": self.params}, x, output=out)

    def num_params(self) -> int:
        import jax
        return sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(self.params))


PREPROCESSORS: dict[str, Callable[[Any], Any]] = {}


def register_preprocess(name: str):
    def deco(fn):
        PREPROCESSORS[name] = fn
        return fn
    return deco


@register_preprocess("scale_01")
def _scale_01(x):
    return x / 255.0


@register_preprocess("center_128")
def _center_128(x):
    # CIFAR CNTK models center pixels around 0 by subtracting the mean image;
    # a constant 128 shift is the stand-in used by notebook 301's pipeline
    return x - 128.0


@register_preprocess("imagenet_norm")
def _imagenet_norm(x):
    # standard ImageNet channel statistics on 0-255 RGB input
    import jax.numpy as jnp
    mean = jnp.asarray([123.675, 116.28, 103.53], x.dtype)
    std = jnp.asarray([58.395, 57.12, 57.375], x.dtype)
    return (x - mean) / std


@register_preprocess("scale_pm1")
def _scale_pm1(x):
    # 0-255 -> [-1, 1] (the ViT checkpoint-family convention)
    return x / 127.5 - 1.0

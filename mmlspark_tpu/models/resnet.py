"""ResNet-50 — the transfer-learning workhorse of the reference zoo.

The reference serves a pretrained CNTK ResNet-50 through its model zoo and
cuts layers off it for featurization (reference:
downloader/src/main/scala/Schema.scala:54-74,
image-featurizer/src/main/scala/ImageFeaturizer.scala:116-140; BASELINE
config 3 "ResNet-50 ImageFeaturizer"). TPU-first choices:

* NHWC layout, bfloat16 compute, float32 params.
* **GroupNorm instead of BatchNorm**: batch statistics are mutable state
  that must all-reduce across every dp replica each step — cross-host sync
  the functional JAX train step doesn't need. GroupNorm(32) is the standard
  stateless substitute (same parameter count/shape role) and keeps a model
  a pure ``params`` pytree end to end (checkpoints, bundles, featurizer
  cuts all stay trivial).
* Fully convolutional + global average pool, so featurization works at any
  input size the pipeline resizes to.

Output nodes: ``features`` (pooled 2048-d embedding, the featurizer cut)
and ``logits``.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with projection shortcut (ResNet v1.5:
    the stride lives on the 3×3)."""

    filters: int
    strides: int = 1
    groups: int = 32
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.GroupNorm(num_groups=self.groups, dtype=self.dtype,
                         name="gn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        y = nn.GroupNorm(num_groups=self.groups, dtype=self.dtype,
                         name="gn2")(y)
        y = nn.relu(y)
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv3")(y)
        y = nn.GroupNorm(num_groups=self.groups, dtype=self.dtype,
                         name="gn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(4 * self.filters, (1, 1),
                               strides=(self.strides,) * 2, use_bias=False,
                               dtype=self.dtype, name="proj")(x)
            residual = nn.GroupNorm(num_groups=self.groups,
                                    dtype=self.dtype, name="gn_proj")(
                residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet v1.5 with bottleneck blocks; stage_sizes (3,4,6,3) = ResNet-50."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    width: int = 64
    groups: int = 32
    dtype: Any = jnp.bfloat16

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=self.dtype, name="conv_stem")(x)
        x = nn.GroupNorm(num_groups=min(self.groups, self.width),
                         dtype=self.dtype, name="gn_stem")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2 ** stage)
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters=filters, strides=strides,
                    groups=min(self.groups, filters),
                    dtype=self.dtype,
                    name=f"stage{stage}_block{block}")(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        features = x.astype(jnp.float32)
        if output == "features":
            return features
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)


def resnet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(num_classes=num_classes, stage_sizes=(3, 4, 6, 3),
                  dtype=dtype)


def resnet18_thin(num_classes: int = 10, width: int = 16,
                  dtype: Any = jnp.bfloat16) -> ResNet:
    """Small same-shape-family net for tests/CI (bottleneck (2,2) stages)."""
    return ResNet(num_classes=num_classes, stage_sizes=(2, 2), width=width,
                  groups=8, dtype=dtype)

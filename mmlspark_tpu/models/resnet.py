"""ResNet-50 — the transfer-learning workhorse of the reference zoo.

The reference serves a pretrained CNTK ResNet-50 through its model zoo and
cuts layers off it for featurization (reference:
downloader/src/main/scala/Schema.scala:54-74,
image-featurizer/src/main/scala/ImageFeaturizer.scala:116-140; BASELINE
config 3 "ResNet-50 ImageFeaturizer"). TPU-first choices:

* NHWC layout, bfloat16 compute, float32 params.
* **GroupNorm instead of BatchNorm**: batch statistics are mutable state
  that must all-reduce across every dp replica each step — cross-host sync
  the functional JAX train step doesn't need. GroupNorm(32) is the standard
  stateless substitute (same parameter count/shape role) and keeps a model
  a pure ``params`` pytree end to end (checkpoints, bundles, featurizer
  cuts all stay trivial).
* Fully convolutional + global average pool, so featurization works at any
  input size the pipeline resizes to.

Output nodes: ``features`` (pooled 2048-d embedding, the featurizer cut)
and ``logits``.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class _PallasGN(nn.Module):
    """GroupNorm(+fused ReLU) through the Pallas kernel, with the same
    param names/shapes as ``nn.GroupNorm`` so published bundles and
    checkpoints load interchangeably (the kernel auto-falls back to the
    XLA lowering for blocks too large for VMEM)."""

    num_groups: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, relu: bool = False):
        from mmlspark_tpu.ops.group_norm import group_norm
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        return group_norm(x, scale, bias, self.num_groups,
                          relu=relu).astype(self.dtype)


def _gn(name: str, groups: int, dtype: Any, impl: str, y, relu: bool = False):
    """One GroupNorm site: the default XLA path is byte-identical to before
    (plain nn.GroupNorm); ``impl="pallas"`` swaps in the fused kernel."""
    if impl == "pallas":
        return _PallasGN(num_groups=groups, dtype=dtype, name=name)(y, relu)
    if impl != "xla":
        raise ValueError(f"unknown gn_impl {impl!r}; one of ['xla', "
                         "'pallas']")
    y = nn.GroupNorm(num_groups=groups, dtype=dtype, name=name)(y)
    return nn.relu(y) if relu else y


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with projection shortcut (ResNet v1.5:
    the stride lives on the 3×3)."""

    filters: int
    strides: int = 1
    groups: int = 32
    dtype: Any = jnp.bfloat16
    gn_impl: str = "xla"

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = _gn("gn1", self.groups, self.dtype, self.gn_impl, y, relu=True)
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        y = _gn("gn2", self.groups, self.dtype, self.gn_impl, y, relu=True)
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv3")(y)
        y = _gn("gn3", self.groups, self.dtype, self.gn_impl, y)
        if residual.shape != y.shape:
            residual = nn.Conv(4 * self.filters, (1, 1),
                               strides=(self.strides,) * 2, use_bias=False,
                               dtype=self.dtype, name="proj")(x)
            residual = _gn("gn_proj", self.groups, self.dtype,
                           self.gn_impl, residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet v1.5 with bottleneck blocks; stage_sizes (3,4,6,3) = ResNet-50."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    width: int = 64
    groups: int = 32
    dtype: Any = jnp.bfloat16
    gn_impl: str = "xla"   # "pallas" = fused GN+ReLU kernel (ops/group_norm)

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=self.dtype, name="conv_stem")(x)
        x = _gn("gn_stem", min(self.groups, self.width), self.dtype,
                self.gn_impl, x, relu=True)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2 ** stage)
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters=filters, strides=strides,
                    groups=min(self.groups, filters),
                    dtype=self.dtype, gn_impl=self.gn_impl,
                    name=f"stage{stage}_block{block}")(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        features = x.astype(jnp.float32)
        if output == "features":
            return features
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)


def resnet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
             gn_impl: str = "xla") -> ResNet:
    return ResNet(num_classes=num_classes, stage_sizes=(3, 4, 6, 3),
                  dtype=dtype, gn_impl=gn_impl)


def resnet18_thin(num_classes: int = 10, width: int = 16,
                  dtype: Any = jnp.bfloat16, gn_impl: str = "xla") -> ResNet:
    """Small same-shape-family net for tests/CI (bottleneck (2,2) stages)."""
    return ResNet(num_classes=num_classes, stage_sizes=(2, 2), width=width,
                  groups=8, dtype=dtype, gn_impl=gn_impl)

"""ResNet-50 — the transfer-learning workhorse of the reference zoo.

The reference serves a pretrained CNTK ResNet-50 through its model zoo and
cuts layers off it for featurization (reference:
downloader/src/main/scala/Schema.scala:54-74,
image-featurizer/src/main/scala/ImageFeaturizer.scala:116-140; BASELINE
config 3 "ResNet-50 ImageFeaturizer"). TPU-first choices:

* NHWC layout, bfloat16 compute, float32 params.
* Three norm modes (``norm=``):
  - ``"group"`` (train default): batch statistics are mutable state that
    must all-reduce across every dp replica each step — cross-host sync
    the functional JAX train step doesn't need. GroupNorm(32) is the
    standard stateless substitute and keeps a model a pure ``params``
    pytree end to end (checkpoints, bundles, featurizer cuts all stay
    trivial).
  - ``"batch"``: classic BatchNorm, matching the reference zoo's
    pretrained ResNet-50 (a BN network — reference:
    downloader/src/main/scala/Schema.scala:54-74). Used transiently at
    bundle-publish time; carries a ``batch_stats`` collection.
  - ``"none"``: the **folded inference variant** — no norm ops at all;
    each conv is followed by an explicit float32 bias-add site
    (``fold*`` — :class:`_FoldedBias`). :func:`fold_batchnorm` converts
    a trained ``"batch"`` net into this form algebraically (frozen BN
    statistics fold into the conv weights: ``W' = W·γ/√(σ²+ε)``,
    ``b' = β − μγ/√(σ²+ε)``), so frozen-backbone featurization pays
    **zero** norm HBM traffic — each activation is written once by its
    conv (bias+ReLU fused into the epilogue by XLA) instead of being
    re-read for per-sample normalization. The μ/σ-derived bias stays
    f32 even in a bf16 net (the add is the BN centering: in bf16 it
    cancels catastrophically against trained-scale conv outputs).
* Fully convolutional + global average pool, so featurization works at any
  input size the pipeline resizes to.

Output nodes: ``features`` (pooled 2048-d embedding, the featurizer cut)
and ``logits``.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Sequence

import jax
import numpy as np

import jax.numpy as jnp
from flax import linen as nn


class _PallasGN(nn.Module):
    """GroupNorm(+fused ReLU) through the Pallas kernel, with the same
    param names/shapes as ``nn.GroupNorm`` so published bundles and
    checkpoints load interchangeably (the kernel auto-falls back to the
    XLA lowering for blocks too large for VMEM)."""

    num_groups: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, relu: bool = False):
        from mmlspark_tpu.ops.group_norm import group_norm
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        return group_norm(x, scale, bias, self.num_groups,
                          relu=relu).astype(self.dtype)


def _gn(name: str, groups: int, dtype: Any, impl: str, y, relu: bool = False):
    """One GroupNorm site: the default XLA path is byte-identical to before
    (plain nn.GroupNorm); ``impl="pallas"`` swaps in the fused kernel."""
    if impl == "pallas":
        return _PallasGN(num_groups=groups, dtype=dtype, name=name)(y, relu)
    if impl != "xla":
        raise ValueError(f"unknown gn_impl {impl!r}; one of ['xla', "
                         "'pallas']")
    y = nn.GroupNorm(num_groups=groups, dtype=dtype, name=name)(y)
    return nn.relu(y) if relu else y


class _FoldedBias(nn.Module):
    """The folded-BN constant site of a ``norm="none"`` net.

    Holds the μ/σ-derived bias ``β − μγ/√(σ²+ε)`` (:func:`fold_batchnorm`)
    as an EXPLICIT float32 param and performs the add in float32 before
    casting back to the compute dtype. Inside the conv (the previous
    layout) a ``dtype=bf16`` net quantized the constant AND the add to
    bf16 — for trained statistics the conv output and its centering bias
    are large near-cancelling values, so the normalization numerics
    silently degraded (the same accumulate-in-f32 contract
    ``ops/group_norm.py`` keeps). The bias is C values per site: keeping
    it f32 costs nothing against the bf16 kernel HBM win."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, relu: bool = False):
        bias = self.param("bias", nn.initializers.zeros,
                          (x.shape[-1],), jnp.float32)
        y = x.astype(jnp.float32) + bias
        if relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(self.dtype)


class _NormCtx:
    """Per-site norm dispatch shared by the stem and the blocks."""

    def __init__(self, norm: str, groups: int, dtype: Any, gn_impl: str,
                 train: bool):
        if norm not in ("group", "batch", "none"):
            raise ValueError(f"unknown norm {norm!r}; one of "
                             "['group', 'batch', 'none']")
        self.norm, self.groups, self.dtype = norm, groups, dtype
        self.gn_impl, self.train = gn_impl, train

    @property
    def conv_bias(self) -> bool:
        # no conv ever carries a bias: folded nets hold the BN-derived
        # constant at an explicit f32 add site (_FoldedBias) instead —
        # a bias inside a dtype=bf16 conv is added in bf16
        return False

    def __call__(self, site: str, y, relu: bool = False):
        """``site`` is the conv name; norm params live at its paired name
        (conv1→gn1/bn1, proj→gn_proj/bn_proj, conv_stem→gn_stem/bn_stem;
        folded nets: conv1→fold1 …)."""
        pair = _NORM_PAIRS[site]
        if self.norm == "none":
            return _FoldedBias(dtype=self.dtype,
                               name="fold" + pair)(y, relu)
        if self.norm == "batch":
            y = nn.BatchNorm(use_running_average=not self.train,
                             momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                             name="bn" + pair)(y)
            return nn.relu(y) if relu else y
        groups = min(self.groups, y.shape[-1])
        return _gn("gn" + pair, groups, self.dtype, self.gn_impl, y, relu)


# conv site -> norm-name suffix ("gn"/"bn" + suffix)
_NORM_PAIRS = {"conv_stem": "_stem", "conv1": "1", "conv2": "2",
               "conv3": "3", "proj": "_proj"}


class _S2DStem(nn.Module):
    """The 7×7/s2 RGB stem in space-to-depth form — numerically identical,
    MXU-shaped (the MLPerf-TPU ResNet trick).

    A direct stem conv contracts over just 7·7·3 = 147 taps of 3-channel
    input — the MXU's 128 input lanes run 3/128 full. Space-to-depth by 2
    turns the same op into a 4×4 stride-1 conv over a 12-channel grid
    (contraction 192, lanes 12/128 → 4× denser, half the spatial extent).
    Parameters keep the standard ``nn.Conv`` layout ((7,7,cin,F) kernel
    [+ bias]), assembled into block form at trace time, so checkpoints are
    interchangeable with the direct formulation; zero entries encode taps
    that fall outside the 7×7 window.

    Derivation: SAME padding for k=7,s=2 on even H pads (2,3), so
    ``out[i,j] = Σ_{a,b∈[0,7)} in[2i+a−2, 2j+b−2]·W[a,b]``. With the s2d
    grid ``S[p,q,(u,v,c)] = in[2p+u, 2q+v, c]`` the raw row 2i+a−2 is s2d
    row ``i+dp, u`` with ``a = 2dp+u+2``, dp ∈ [−1,2] — a 4×4 window at
    stride 1 with padding (1,2).
    """

    features: int
    use_bias: bool
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        cin, F = x.shape[-1], self.features
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (7, 7, cin, F))
        bias = (self.param("bias", nn.initializers.zeros, (F,))
                if self.use_bias else None)
        B, H, W = x.shape[0], x.shape[1], x.shape[2]
        if H % 2 or W % 2:
            raise ValueError(f"_S2DStem needs even H/W, got {H}x{W}")
        k = kernel.astype(self.dtype)
        wb = jnp.zeros((4, 4, 2, 2, cin, F), self.dtype)
        for dp in range(-1, 3):
            for u in range(2):
                a = 2 * dp + u + 2
                if not 0 <= a < 7:
                    continue
                for dq in range(-1, 3):
                    for v in range(2):
                        b = 2 * dq + v + 2
                        if not 0 <= b < 7:
                            continue
                        wb = wb.at[dp + 1, dq + 1, u, v].set(k[a, b])
        wb = wb.reshape(4, 4, 4 * cin, F)
        h, w = H // 2, W // 2
        xs = x.astype(self.dtype).reshape(B, h, 2, w, 2, cin)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(B, h, w, 4 * cin)
        y = jax.lax.conv_general_dilated(
            xs, wb, window_strides=(1, 1), padding=((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + bias.astype(self.dtype) if bias is not None else y


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with projection shortcut (ResNet v1.5:
    the stride lives on the 3×3)."""

    filters: int
    strides: int = 1
    groups: int = 32
    dtype: Any = jnp.bfloat16
    gn_impl: str = "xla"
    norm: str = "group"

    @nn.compact
    def __call__(self, x, train: bool = False):
        ctx = _NormCtx(self.norm, self.groups, self.dtype, self.gn_impl,
                       train)
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=ctx.conv_bias,
                    dtype=self.dtype, name="conv1")(x)
        y = ctx("conv1", y, relu=True)
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                    use_bias=ctx.conv_bias, dtype=self.dtype, name="conv2")(y)
        y = ctx("conv2", y, relu=True)
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=ctx.conv_bias,
                    dtype=self.dtype, name="conv3")(y)
        y = ctx("conv3", y)
        if residual.shape != y.shape:
            residual = nn.Conv(4 * self.filters, (1, 1),
                               strides=(self.strides,) * 2,
                               use_bias=ctx.conv_bias,
                               dtype=self.dtype, name="proj")(x)
            residual = ctx("proj", residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet v1.5 with bottleneck blocks; stage_sizes (3,4,6,3) = ResNet-50."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    width: int = 64
    groups: int = 32
    dtype: Any = jnp.bfloat16
    gn_impl: str = "xla"   # "pallas" = fused GN+ReLU kernel (ops/group_norm)
    norm: str = "group"    # "group" | "batch" (publish) | "none" (folded)
    stem: str = "direct"   # "direct" | "s2d" (MXU-shaped, same params)

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, x, output: str = "logits", train: bool = False):
        ctx = _NormCtx(self.norm, min(self.groups, self.width), self.dtype,
                       self.gn_impl, train)
        x = x.astype(self.dtype)
        # the s2d block form needs even H/W; odd inputs fall back to the
        # direct conv — SAME param layout, so the any-input-size contract
        # holds for every stem choice
        if self.stem == "s2d" and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = _S2DStem(self.width, use_bias=ctx.conv_bias,
                         dtype=self.dtype, name="conv_stem")(x)
        else:
            x = nn.Conv(self.width, (7, 7), strides=(2, 2),
                        use_bias=ctx.conv_bias,
                        dtype=self.dtype, name="conv_stem")(x)
        x = ctx("conv_stem", x, relu=True)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2 ** stage)
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters=filters, strides=strides,
                    groups=min(self.groups, filters),
                    dtype=self.dtype, gn_impl=self.gn_impl, norm=self.norm,
                    name=f"stage{stage}_block{block}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        features = x.astype(jnp.float32)
        if output == "features":
            return features
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)


def resnet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
             gn_impl: str = "xla", norm: str = "group",
             stem: str = "direct") -> ResNet:
    return ResNet(num_classes=num_classes, stage_sizes=(3, 4, 6, 3),
                  dtype=dtype, gn_impl=gn_impl, norm=norm, stem=stem)


def resnet18_thin(num_classes: int = 10, width: int = 16,
                  dtype: Any = jnp.bfloat16, gn_impl: str = "xla",
                  norm: str = "group", stem: str = "direct") -> ResNet:
    """Small same-shape-family net for tests/CI (bottleneck (2,2) stages)."""
    return ResNet(num_classes=num_classes, stage_sizes=(2, 2), width=width,
                  groups=8, dtype=dtype, gn_impl=gn_impl, norm=norm,
                  stem=stem)


# ---- frozen-BN folding (inference variant) --------------------------------

def fold_batchnorm(variables: Any, eps: float = 1e-5,
                   param_dtype: Any = None) -> Any:
    """Fold a trained ``norm="batch"`` ResNet's frozen BN statistics into
    its conv weights, producing the params tree of the same architecture
    with ``norm="none"``.

    For conv ``W`` (no bias) followed by BN ``(γ, β, μ, σ²)`` in inference
    mode::

        y = γ·(Wx − μ)/√(σ²+ε) + β  =  (W·γ/√(σ²+ε))·x + (β − μγ/√(σ²+ε))

    so the folded net computes *identical* math with zero norm ops — the
    reference's zoo ResNet-50 is exactly such a BN network whose inference
    cost folds away (reference: downloader/src/main/scala/Schema.scala:54-74,
    ImageFeaturizer.scala:116-140). The fold arithmetic runs in float64;
    the μ/σ-derived bias lands at the net's ``fold*`` sites
    (:class:`_FoldedBias`) and ALWAYS stays float32 — ``param_dtype``
    (bf16 halves inference HBM weight traffic) casts only the ≥2-D conv/
    dense kernels, never the folded normalization constants, so a bf16
    inference variant keeps its mean/variance accumulation in f32 (the
    ``ops/group_norm.py`` contract; regression-pinned against the f64
    oracle in tests/test_ops.py).

    LAYOUT NOTE (round 12): folded trees previously stored the bias
    inside the conv subtree (``{conv1: {kernel, bias}}``); it now lives
    at the sibling ``fold*`` site (``{conv1: {kernel}, fold1: {bias}}``)
    matching the ``norm="none"`` architecture's :class:`_FoldedBias`
    params. The in-repo zoo/publish paths fold at load so nothing
    in-tree is affected, but a folded bundle PUBLISHED to a model repo
    before this round must be re-published (re-fold from its BN source;
    loading the old layout fails with a flax param-structure mismatch).
    """
    params, stats = variables["params"], variables["batch_stats"]

    def fold(p: dict, s: dict) -> dict:
        out = {}
        for key, val in p.items():
            if key.startswith("bn"):
                continue  # consumed by its conv
            bn_key = ("bn" + _NORM_PAIRS[key]) if key in _NORM_PAIRS \
                else None
            if bn_key and bn_key in p:
                bn, st = p[bn_key], s[bn_key]
                inv = np.asarray(bn["scale"], np.float64) / np.sqrt(
                    np.asarray(st["var"], np.float64) + eps)
                kernel = np.asarray(val["kernel"], np.float64) * inv
                bias = (np.asarray(bn["bias"], np.float64)
                        - np.asarray(st["mean"], np.float64) * inv)
                out[key] = {"kernel": jnp.asarray(kernel, jnp.float32)}
                out["fold" + _NORM_PAIRS[key]] = {
                    "bias": jnp.asarray(bias, jnp.float32)}
            elif isinstance(val, Mapping):
                out[key] = fold(val, s.get(key, {}))
            else:
                out[key] = val
        return out

    folded = fold(params, stats)
    if param_dtype is not None:
        # kernels only: 1-D leaves (dense biases, the fold* constants)
        # keep f32 accumulation — see the docstring contract
        folded = jax.tree_util.tree_map(
            lambda a: (jnp.asarray(a, param_dtype)
                       if getattr(a, "ndim", 0) >= 2
                       else jnp.asarray(a, jnp.float32)), folded)
    return folded

"""Sequence model family: BiLSTM tagger and Transformer encoder.

The reference's sequence workload is notebook 304 (Medical Entity
Extraction): a pretrained CNTK BiLSTM run token-tagged sentences padded
host-side to a fixed 613 tokens, minibatch 1 (reference:
notebooks/samples/304 - Medical Entity Extraction.ipynb). The TPU-native
family:

* :class:`BiLSTMTagger` — embeddings → forward+backward LSTM (``nn.RNN``
  over ``lax.scan``, compiler-friendly recurrence) → per-token logits.
  Padded/bucketed *batches* replace minibatch-1 (see
  :func:`bucket_batches`).
* :class:`TransformerTagger` — encoder blocks whose attention is pluggable:
  local (single device) or sequence-parallel ring/Ulysses over the ``sp``
  mesh axis (:mod:`mmlspark_tpu.parallel.ring_attention`) for long
  sequences.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np
import flax.linen as nn
import jax.numpy as jnp


class BiLSTMTagger(nn.Module):
    """Per-token classification over embedded sequences."""

    vocab_size: int = 1024
    embed_dim: int = 64
    hidden: int = 128
    num_tags: int = 8
    dtype: Any = jnp.float32
    # lax.scan unroll factor for the recurrence: an RNN step's matmuls are
    # tiny, so per-iteration loop overhead dominates — unrolling 16 steps
    # per scan iteration measured 11.7 → 25.0M tokens/s at B=64/L=613 on
    # v5e (knee at 16; 64+ regresses and blows up compile time,
    # PERF_NOTES round 5). Params are unaffected — execution detail only
    unroll: int = 16

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, tokens, output: str = "logits", train: bool = False,
                 mask=None):
        # tokens: [B, L] int32; mask: [B, L] bool (True = real token) — the
        # backward LSTM must start at each row's true end, not at the pad
        x = nn.Embed(self.vocab_size, self.embed_dim, name="embed")(
            tokens.astype(jnp.int32))
        seq_lengths = (jnp.sum(mask.astype(jnp.int32), axis=1)
                       if mask is not None else None)
        fwd = nn.RNN(nn.LSTMCell(self.hidden), unroll=self.unroll,
                     name="lstm_fwd")(
            x, seq_lengths=seq_lengths)
        bwd = nn.RNN(nn.LSTMCell(self.hidden), reverse=True,
                     keep_order=True, unroll=self.unroll, name="lstm_bwd")(
            x, seq_lengths=seq_lengths)
        h = jnp.concatenate([fwd, bwd], axis=-1)
        if output == "features":
            return h
        return nn.Dense(self.num_tags, name="head")(h)


class TransformerTagger(nn.Module):
    """Small encoder for per-token or pooled outputs; attention impl is
    selected by name so the same params run single-device or
    sequence-parallel."""

    vocab_size: int = 1024
    embed_dim: int = 64
    num_heads: int = 4
    num_layers: int = 2
    mlp_dim: int = 128
    num_tags: int = 8
    max_len: int = 2048
    causal: bool = False
    dtype: Any = jnp.float32
    # > 0 swaps each layer's dense MLP for a Switch-style top-1
    # mixture-of-experts FFN (parallel/moe param layout). Single-device
    # it routes densely; pass ``moe_fn`` (e.g. a closure over
    # parallel.moe.moe_apply and an ep mesh) to run the expert-parallel
    # all-to-all dispatch with the SAME params. Per-layer load-balance
    # aux losses are sown under intermediates/"moe_aux"
    moe_experts: int = 0
    # per-expert capacity headroom for the expert-parallel dispatch
    # (parallel/moe.py); tokens over capacity pass through the residual
    moe_capacity_factor: float = 2.0
    # when set and no explicit mask is passed, tokens equal to this id
    # are treated as padding (the bucketing helpers pad with 0) — how
    # padding-awareness reaches callers that can't thread a mask kwarg,
    # e.g. Trainer.fit_arrays feeding plain (tokens, tags) batches
    pad_token_id: int | None = None

    OUTPUT_NAMES = ("features", "logits")

    @nn.compact
    def __call__(self, tokens, output: str = "logits", train: bool = False,
                 attention_fn: Callable | None = None, mask=None,
                 moe_fn: Callable | None = None, cache=None, positions=None,
                 update_mask=None, return_cache: bool = False,
                 decode_attention_fn: Callable | None = None):
        # mask: [B, L] bool (True = real token); pad keys are excluded from
        # attention so logits don't depend on the bucket's padding amount.
        # attention_fn receives (q, k, v, kv_mask, causal) so a
        # causal-configured model stays causal on the sequence-parallel
        # path — ring_attention/ulysses_attention take the same kwargs.
        #
        # Autoregressive decode (serve/generate.py) threads a slot-major
        # KV-cache through the SAME params:
        #
        # * ``return_cache=True`` (prefill): the full causal forward
        #   additionally returns every layer's K/V stacked
        #   ``[B, layers, H, L, head_dim]`` — what the serve prefill
        #   program scatters into assigned cache slots;
        # * ``cache=(ck, cv)`` (decode): ``tokens`` is ``[S, 1]`` (one new
        #   token per slot), ``positions`` ``[S]`` is each slot's write
        #   index (== its current length), and the caches are
        #   ``[S, layers, H, T_max, head_dim]``. The new token's K/V is
        #   written at ``positions`` (rows where ``update_mask`` is False
        #   keep their cache untouched — the inactive-slot guard of the
        #   fixed-shape decode program), attention runs ``q_len=1``
        #   against the cache through ``decode_attention_fn`` (default
        #   :func:`~mmlspark_tpu.ops.pallas.attention.decode_attention`),
        #   and the call returns ``(logits [S, num_tags], (ck', cv'))``.
        if cache is not None:
            return self._decode_step(tokens, cache, positions, update_mask,
                                     moe_fn, decode_attention_fn)
        B, L = tokens.shape
        if mask is None and self.pad_token_id is not None:
            mask = tokens.astype(jnp.int32) != self.pad_token_id
        x = nn.Embed(self.vocab_size, self.embed_dim, name="embed")(
            tokens.astype(jnp.int32))
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (self.max_len, self.embed_dim))
        x = x + pos[None, :L]
        head_dim = self.embed_dim // self.num_heads
        kv_layers: list = []
        for i in range(self.num_layers):
            h = nn.LayerNorm(name=f"ln_a{i}")(x)
            qkv = nn.Dense(3 * self.embed_dim, name=f"qkv{i}")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, L, self.num_heads, head_dim)
            k = k.reshape(B, L, self.num_heads, head_dim)
            v = v.reshape(B, L, self.num_heads, head_dim)
            if return_cache:
                # [B, H, L, head_dim] — the slot-major cache layer slice
                kv_layers.append((k.transpose(0, 2, 1, 3),
                                  v.transpose(0, 2, 1, 3)))
            if attention_fn is None:
                from mmlspark_tpu.parallel.ring_attention import (
                    attention_reference,
                )
                attn = attention_reference(q, k, v, causal=self.causal,
                                           kv_mask=mask)
            else:
                attn = attention_fn(q, k, v, mask, self.causal)
            attn = attn.reshape(B, L, self.embed_dim)
            x = x + nn.Dense(self.embed_dim, name=f"proj{i}")(attn)
            h = nn.LayerNorm(name=f"ln_b{i}")(x)
            if self.moe_experts > 0:
                x = x + self._moe_ffn(h, i, moe_fn, mask)
            else:
                h = nn.Dense(self.mlp_dim, name=f"mlp_in{i}")(h)
                h = nn.gelu(h)
                x = x + nn.Dense(self.embed_dim, name=f"mlp_out{i}")(h)
        x = nn.LayerNorm(name="ln_f")(x)
        out = x if output == "features" \
            else nn.Dense(self.num_tags, name="head")(x)
        if return_cache:
            ck = jnp.stack([k for k, _ in kv_layers], axis=1)
            cv = jnp.stack([v for _, v in kv_layers], axis=1)
            return out, (ck, cv)
        return out

    def _decode_step(self, tokens, cache, positions, update_mask, moe_fn,
                     decode_attention_fn):
        """One token step against the slot-major KV-cache — the body of
        the serve plane's ONE fixed-shape decode program. Same submodule
        names (and therefore the same params) as the full forward."""
        if decode_attention_fn is None:
            from mmlspark_tpu.ops.pallas.attention import decode_attention
            decode_attention_fn = decode_attention
        ck, cv = cache
        S = tokens.shape[0]
        T = ck.shape[3]
        head_dim = self.embed_dim // self.num_heads
        positions = positions.astype(jnp.int32)
        x = nn.Embed(self.vocab_size, self.embed_dim, name="embed")(
            tokens.astype(jnp.int32))          # [S, 1, D]
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (self.max_len, self.embed_dim))
        x = x + jnp.take(pos, positions, axis=0)[:, None, :]
        rows = jnp.arange(S)
        # the new position becomes visible to its own query (inclusive)
        keep = jnp.arange(T)[None, :] <= positions[:, None]
        if update_mask is not None:
            keep = keep & update_mask[:, None]
            sel = update_mask[:, None, None, None, None]
        for i in range(self.num_layers):
            h = nn.LayerNorm(name=f"ln_a{i}")(x)
            qkv = nn.Dense(3 * self.embed_dim, name=f"qkv{i}")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(S, self.num_heads, head_dim)
            k = k.reshape(S, self.num_heads, head_dim)
            v = v.reshape(S, self.num_heads, head_dim)
            # functional in-place write at each slot's position; rows
            # outside update_mask keep their old cache bits exactly (an
            # inactive slot's stale position must never clobber a row a
            # concurrent prefill just filled)
            ck_new = ck.at[rows, i, :, positions].set(k)
            cv_new = cv.at[rows, i, :, positions].set(v)
            if update_mask is not None:
                ck = jnp.where(sel, ck_new, ck)
                cv = jnp.where(sel, cv_new, cv)
            else:
                ck, cv = ck_new, cv_new
            attn = decode_attention_fn(q, ck[:, i], cv[:, i], keep)
            attn = attn.astype(x.dtype).reshape(S, 1, self.embed_dim)
            x = x + nn.Dense(self.embed_dim, name=f"proj{i}")(attn)
            h = nn.LayerNorm(name=f"ln_b{i}")(x)
            if self.moe_experts > 0:
                x = x + self._moe_ffn(h, i, moe_fn, None)
            else:
                h = nn.Dense(self.mlp_dim, name=f"mlp_in{i}")(h)
                h = nn.gelu(h)
                x = x + nn.Dense(self.embed_dim, name=f"mlp_out{i}")(h)
        x = nn.LayerNorm(name="ln_f")(x)
        logits = nn.Dense(self.num_tags, name="head")(x)[:, 0]
        return logits, (ck, cv)

    def mesh_hooks(self, mesh) -> dict:
        """Trainer integration (train/loop.py:resolve_mesh_hooks): on an
        ``sp > 1`` mesh attention runs as the ring collective; on an
        ``ep > 1`` mesh (with ``moe_experts > 0``) the MoE FFNs dispatch
        expert-parallel via all-to-all, expert params sharded over ``ep``.
        Same params as the single-device paths — parallelism is an
        execution detail, not a model change."""
        from jax.sharding import PartitionSpec as P

        kwargs: dict = {}
        handled: set = set()
        rules = None
        if mesh.shape.get("sp", 1) > 1:
            from mmlspark_tpu.parallel.ring_attention import ring_attention

            def attention_fn(q, k, v, kv_mask, causal, _mesh=mesh):
                return ring_attention(q, k, v, _mesh, causal=causal,
                                      kv_mask=kv_mask)

            kwargs["attention_fn"] = attention_fn
            handled.add("sp")
        if mesh.shape.get("ep", 1) > 1 and self.moe_experts > 0:
            from mmlspark_tpu.parallel.moe import moe_apply

            def moe_fn(params, x, token_mask, _mesh=mesh):
                return moe_apply(params, x, _mesh,
                                 capacity_factor=self.moe_capacity_factor,
                                 token_mask=token_mask)

            kwargs["moe_fn"] = moe_fn
            handled.add("ep")

            def rules(path: str, leaf):
                # stacked expert FFNs shard over ep on the expert axis;
                # the gate stays under the generic rules (replicated)
                name = path.rsplit("/", 1)[-1]
                if name.startswith("moe") and name.endswith(
                        ("_w_in", "_b_in", "_w_out", "_b_out")):
                    return P("ep")
                return None
        return {"apply_kwargs": kwargs, "param_rules": rules,
                "handled": handled}

    def _moe_ffn(self, h, i: int, moe_fn: Callable | None, mask):
        """Switch MoE FFN for layer ``i`` — params in the
        ``parallel/moe`` layout (gate + expert-stacked FFN), routed
        densely by default or through ``moe_fn`` for expert parallelism.
        The padding mask rides along so pad tokens never claim capacity
        slots (the padding invariant: a sentence's logits must not depend
        on its bucket's pad amount)."""
        from mmlspark_tpu.parallel.moe import moe_dense

        B, L, D = h.shape
        E = self.moe_experts
        dh = self.mlp_dim
        init = nn.initializers.lecun_normal()
        params = {
            "gate": self.param(f"moe{i}_gate", init, (D, E)),
            "w_in": self.param(f"moe{i}_w_in", init, (E, D, dh)),
            "b_in": self.param(f"moe{i}_b_in", nn.initializers.zeros,
                               (E, dh)),
            "w_out": self.param(f"moe{i}_w_out", init, (E, dh, D)),
            "b_out": self.param(f"moe{i}_b_out", nn.initializers.zeros,
                                (E, D)),
        }
        flat = h.reshape(B * L, D)
        flat_mask = None if mask is None else mask.reshape(B * L)
        y, aux = (moe_fn or moe_dense)(params, flat, flat_mask)
        self.sow("intermediates", "moe_aux", aux)
        return y.reshape(B, L, D)


# ---- padded/bucketed batching (the 613-token fixed pad, generalized) ----

def _check_sequence(i: int, s) -> np.ndarray:
    """Validate one token sequence; returns it as an int32 array.

    Typed errors instead of silent misshape: an empty sequence would
    produce an all-pad row whose logits are pure padding noise, and
    non-integer tokens would be silently cast by ``np.int32`` (floats
    floor, strings crash deep inside the embed lookup)."""
    arr = np.asarray(s)
    if arr.ndim != 1:
        raise ValueError(
            f"sequence {i} has shape {arr.shape}; expected a flat 1-D "
            "token sequence")
    if arr.size == 0:
        raise ValueError(
            f"sequence {i} is empty; an empty sequence has no tokens to "
            "tag (drop it before batching)")
    if not np.issubdtype(arr.dtype, np.integer):
        if arr.dtype == bool or not np.issubdtype(arr.dtype, np.number) \
                or not np.array_equal(arr, arr.astype(np.int64)):
            raise TypeError(
                f"sequence {i} has non-integer tokens (dtype "
                f"{arr.dtype}); token ids must be integers")
    return arr.astype(np.int32)


def pad_sequences(seqs: Sequence[Sequence[int]], length: int,
                  pad_value: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Pad token sequences to ``length``; returns (tokens, mask).

    Raises ``ValueError`` for empty or overlong sequences and
    ``TypeError`` for non-integer tokens — a sequence longer than
    ``length`` used to be silently truncated, which dropped tokens with
    no signal at all (use :func:`bucket_batches` to pick covering pads).
    """
    out = np.full((len(seqs), length), pad_value, dtype=np.int32)
    mask = np.zeros((len(seqs), length), dtype=bool)
    for i, s in enumerate(seqs):
        arr = _check_sequence(i, s)
        n = arr.shape[0]
        if n > length:
            raise ValueError(
                f"sequence {i} has {n} tokens > pad length {length}; "
                "truncation would silently drop tokens")
        out[i, :n] = arr
        mask[i, :n] = True
    return out, mask


def bucket_batches(seqs: Sequence[Sequence[int]], batch_size: int,
                   bucket_sizes: Sequence[int] = (64, 128, 256, 512, 1024),
                   pad_value: int = 0):
    """Group sequences into fixed-shape padded batches.

    Sequences are bucketed by length to the smallest covering bucket, so XLA
    compiles at most ``len(bucket_sizes)`` programs instead of one per
    unique length — the compilation-model-aware version of the reference's
    single fixed 613-token pad. Yields (tokens [b, bucket], mask, indices)
    with original row indices for order restoration.

    Raises ``ValueError`` when a sequence is empty or exceeds the
    largest bucket (it used to be silently truncated into the top
    bucket) and ``TypeError`` for non-integer tokens.
    """
    # ascending order makes the first covering bucket below the smallest
    bucket_sizes = sorted(bucket_sizes)
    buckets: dict[int, list[int]] = {b: [] for b in bucket_sizes}
    overflow = max(bucket_sizes)
    for i, s in enumerate(seqs):
        n = _check_sequence(i, s).shape[0]
        if n > overflow:
            raise ValueError(
                f"sequence {i} has {n} tokens > largest bucket "
                f"{overflow}; truncation would silently drop tokens")
        for b in bucket_sizes:
            if n <= b:
                buckets[b].append(i)
                break
    for b, idxs in buckets.items():
        for start in range(0, len(idxs), batch_size):
            chunk = idxs[start:start + batch_size]
            toks, mask = pad_sequences([seqs[i] for i in chunk], b,
                                       pad_value)
            yield toks, mask, np.asarray(chunk)

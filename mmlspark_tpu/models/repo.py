"""Versioned model repository — the serving side of checkpoint discipline.

The zoo/downloader layer (``data/downloader.py``, the reference's
``ModelDownloader``) answers "fetch me a model"; a production serve plane
needs the rest of the lifecycle: *which* build of a model is live, how a
new build is published without a reader ever observing a half-written
artifact, and how a corrupt or torn publish is refused instead of served.

Layout (one directory per model, one per version)::

    <root>/<model>/v00001/
                       VERSION.json     # manifest: files + sha256 digests
                       model.bundle     # or a saved-stage tree
    <root>/<model>/v00002/…
    <root>/<model>/CURRENT              # the live version pointer

Guarantees, in the ``TrainCheckpointer`` discipline (PR 11):

* **atomic publish** — a version is staged in a hidden temp dir and
  enters the repo via one ``os.replace``; the ``CURRENT`` pointer is
  rewritten the same way. A crash mid-publish (the
  ``repo_torn_publish`` fault point) leaves the prior version live and
  the temp dir inert — no reader path ever sees a partial version.
* **content digests** — the manifest records a sha256 per file;
  :meth:`ModelRepo.load` re-verifies before deserializing anything, so
  bit-rot, truncation, or a hand-edited artifact is a typed
  :class:`RepoCorruptError`, never a silently-wrong served model.
* **typed errors** — :class:`VersionNotFound` / :class:`RepoCorruptError`
  (both :class:`ModelRepoError`), so ``ModelServer`` keeps serving the
  prior version when a swap source turns out to be bad.

The repo is deliberately a *local directory* contract: ``os.replace``
atomicity is the point. Remote distribution stays the downloader's job.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import shutil
import threading
import time
from typing import Any

from mmlspark_tpu.core.logging_utils import get_logger

_log = get_logger(__name__)


def _faults():
    # lazy: the serve package imports heavily (batcher/server/http), and
    # a training-only job importing mmlspark_tpu.models must not
    # initialize the whole serve plane — the same direction-discipline
    # serve/server.py applies when importing models
    from mmlspark_tpu.serve import faults
    return faults

VERSION_MANIFEST = "VERSION.json"
CURRENT_FILE = "CURRENT"
BUNDLE_FILE = "model.bundle"
STAGE_DIR = "stage"

_VDIR_RE = re.compile(r"^v(\d{5,})$")


class ModelRepoError(Exception):
    """Base of every versioned-repo error."""


class VersionNotFound(ModelRepoError):
    """No such model/version in the repository."""

    def __init__(self, name: str, version: int | None,
                 available: list[int]):
        what = f"version {version}" if version is not None else "versions"
        super().__init__(
            f"model {name!r}: no {what} in the repo "
            f"(available: {available or 'none'})")
        self.name = name
        self.version = version
        self.available = list(available)


class RepoCorruptError(ModelRepoError):
    """A version directory failed integrity verification — missing or
    malformed manifest, a file named by the manifest absent, or a
    content-digest mismatch (torn publish, bit-rot, tampering). The
    version is refused whole; nothing partial is ever deserialized."""

    def __init__(self, name: str, version: int, detail: str):
        super().__init__(
            f"model {name!r} v{version}: corrupt version — {detail}")
        self.name = name
        self.version = version
        self.detail = detail


def _provenance_error(prov: Any) -> str | None:
    """Why ``prov`` is not a valid provenance stamp (None when it is).

    The contract the lifecycle Publisher writes and every reader may
    rely on: source checkpoint step, publisher run/generation id, and
    (optionally) an eval metric excerpt. Checked at publish time (a
    typed :class:`ModelRepoError` — never stage a bad manifest) and
    re-checked on every :meth:`ModelRepo.verify`/``load`` (a
    hand-edited manifest is :class:`RepoCorruptError`, same as a bad
    digest)."""
    if not isinstance(prov, dict):
        return f"not an object ({type(prov).__name__})"
    step = prov.get("checkpoint_step")
    if not isinstance(step, int) or isinstance(step, bool) or step < 0:
        return f"checkpoint_step missing or not a step: {step!r}"
    run_id = prov.get("run_id")
    if not isinstance(run_id, str) or not run_id:
        return f"run_id missing or empty: {run_id!r}"
    generation = prov.get("generation")
    if not isinstance(generation, int) or isinstance(generation, bool) \
            or generation < 0:
        return f"generation missing or not an int: {generation!r}"
    ev = prov.get("eval")
    if ev is not None:
        if not isinstance(ev, dict):
            return f"eval excerpt not an object ({type(ev).__name__})"
        metric = ev.get("metric")
        if metric is not None and not isinstance(metric, (int, float)):
            return f"eval.metric not a number: {metric!r}"
    return None


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_files(root: str) -> list[str]:
    """Every regular file under ``root``, repo-relative, sorted — the
    digest walk must be order-independent of the filesystem."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            full = os.path.join(dirpath, fname)
            out.append(os.path.relpath(full, root))
    return sorted(out)


@dataclasses.dataclass(frozen=True)
class ModelVersion:
    """One verified version's identity (what :meth:`ModelRepo.verify`
    returns): enough to audit a swap decision after the fact."""

    name: str
    version: int
    path: str
    kind: str                    # "bundle" | "stage"
    created: float
    digests: dict
    notes: str = ""
    provenance: dict | None = None  # publisher-stamped: checkpoint
    #                                 step, eval excerpt, run/generation

    def describe(self) -> dict:
        out = {"name": self.name, "version": self.version,
               "kind": self.kind, "created": self.created,
               "files": len(self.digests), "notes": self.notes}
        if self.provenance is not None:
            out["provenance"] = dict(self.provenance)
        return out


class ModelRepo:
    """A versioned model repository rooted at a local directory."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        # publishes from sibling threads (a background trainer and a
        # deploy hook) serialize per process; cross-process safety comes
        # from the atomic renames (last writer wins on CURRENT)
        self._lock = threading.Lock()

    # -- paths --

    def _model_dir(self, name: str) -> str:
        if not name or os.sep in name or name.startswith("."):
            raise ModelRepoError(f"invalid model name {name!r}")
        return os.path.join(self.root, name)

    def _version_dir(self, name: str, version: int) -> str:
        return os.path.join(self._model_dir(name), f"v{version:05d}")

    # -- listing --

    def models(self) -> list[str]:
        """Model names with at least one published version."""
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
            and self.versions(d))

    def versions(self, name: str) -> list[int]:
        """Published (fully renamed-in) versions, ascending. Temp dirs
        and stray files are invisible by construction."""
        mdir = self._model_dir(name)
        if not os.path.isdir(mdir):
            return []
        out = []
        for d in os.listdir(mdir):
            m = _VDIR_RE.match(d)
            if m and os.path.isdir(os.path.join(mdir, d)):
                out.append(int(m.group(1)))
        return sorted(out)

    def current_version(self, name: str) -> int:
        """The live version: the ``CURRENT`` pointer, falling back to
        the newest published version when the pointer is missing or
        points at a version that no longer exists (a pruned dir must
        not brick the model)."""
        versions = self.versions(name)
        if not versions:
            raise VersionNotFound(name, None, [])
        path = os.path.join(self._model_dir(name), CURRENT_FILE)
        try:
            with open(path, "r", encoding="utf-8") as f:
                v = int(f.read().strip())
            if v in versions:
                return v
            _log.warning("repo[%s]: CURRENT points at missing v%d; "
                         "falling back to newest v%d", name, v,
                         versions[-1])
        except (OSError, ValueError):
            pass
        return versions[-1]

    # -- publish --

    def publish(self, name: str, model: Any, notes: str = "",
                set_current: bool = True,
                provenance: dict | None = None) -> int:
        """Publish ``model`` (a ``ModelBundle``, or any stage with
        ``.save``) as the next version; returns the version number.

        The version is staged under a hidden temp dir, digested, and
        renamed in with ``os.replace`` — readers either see the whole
        version or none of it. ``set_current=True`` (default) then
        repoints ``CURRENT`` atomically; ``False`` publishes a dark
        version (for canary-from-repo flows that flip the pointer only
        on promotion). ``provenance`` stamps the publisher's identity
        into the manifest — source checkpoint step, eval metric
        excerpt, run/generation id (the lifecycle Publisher's contract,
        docs/lifecycle.md) — re-validated on every :meth:`verify`."""
        from mmlspark_tpu.models.bundle import ModelBundle
        with self._lock:
            mdir = self._model_dir(name)
            os.makedirs(mdir, exist_ok=True)
            version = (self.versions(name) or [0])[-1] + 1
            vdir = self._version_dir(name, version)
            tmp = os.path.join(mdir, f".staging-v{version:05d}-{os.getpid()}")
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            try:
                if isinstance(model, ModelBundle):
                    from mmlspark_tpu.data.downloader import save_bundle_file
                    save_bundle_file(model, os.path.join(tmp, BUNDLE_FILE))
                    kind = "bundle"
                elif hasattr(model, "save"):
                    model.save(os.path.join(tmp, STAGE_DIR))
                    kind = "stage"
                else:
                    raise ModelRepoError(
                        f"model {name!r}: not publishable "
                        f"({type(model).__name__} is neither a "
                        "ModelBundle nor a savable stage)")
                digests = {rel: _sha256_file(os.path.join(tmp, rel))
                           for rel in _walk_files(tmp)}
                manifest = {"name": name, "version": version,
                            "kind": kind, "created": time.time(),
                            "notes": notes, "files": digests}
                if provenance is not None:
                    err = _provenance_error(provenance)
                    if err:
                        raise ModelRepoError(
                            f"model {name!r}: unpublishable "
                            f"provenance — {err}")
                    manifest["provenance"] = provenance
                with open(os.path.join(tmp, VERSION_MANIFEST), "w",
                          encoding="utf-8") as f:
                    json.dump(manifest, f, indent=1)
                # the torn-publish fault point: a crash here leaves the
                # staging dir (invisible to every reader path) and the
                # prior version live
                _faults().hit("repo_torn_publish", model=name)
                os.replace(tmp, vdir)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            if set_current:
                self._write_current(name, version)
            _log.info("repo[%s]: published v%d (%s, %d file(s))",
                      name, version, kind, len(digests))
            return version

    def _write_current(self, name: str, version: int) -> None:
        path = os.path.join(self._model_dir(name), CURRENT_FILE)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(version))
        os.replace(tmp, path)

    def set_current(self, name: str, version: int) -> None:
        """Repoint ``CURRENT`` (atomic); the repo-side rollback — the
        version must exist and verify."""
        self.verify(name, version)
        with self._lock:
            self._write_current(name, version)

    # -- verify + load --

    def _resolve(self, name: str, version: int | None) -> int:
        if version is None:
            return self.current_version(name)
        if version not in self.versions(name):
            raise VersionNotFound(name, version, self.versions(name))
        return version

    def verify(self, name: str, version: int | None = None) -> ModelVersion:
        """Integrity-check one version against its manifest; returns the
        verified :class:`ModelVersion` or raises
        :class:`RepoCorruptError`. Every byte named by the manifest is
        re-hashed — O(version bytes), the price of never serving a torn
        artifact."""
        version = self._resolve(name, version)
        vdir = self._version_dir(name, version)
        mpath = os.path.join(vdir, VERSION_MANIFEST)
        try:
            with open(mpath, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise RepoCorruptError(name, version,
                                   "manifest missing (torn publish?)")
        except (OSError, ValueError) as e:
            raise RepoCorruptError(name, version,
                                   f"unreadable manifest: {e}")
        files = manifest.get("files")
        if not isinstance(files, dict) or not files:
            raise RepoCorruptError(name, version,
                                   "manifest names no files")
        on_disk = set(_walk_files(vdir)) - {VERSION_MANIFEST}
        missing = sorted(set(files) - on_disk)
        if missing:
            raise RepoCorruptError(
                name, version, f"manifest names missing file(s): "
                f"{missing[:3]}{'…' if len(missing) > 3 else ''}")
        for rel, want in sorted(files.items()):
            got = _sha256_file(os.path.join(vdir, rel))
            if got != want:
                raise RepoCorruptError(
                    name, version,
                    f"digest mismatch on {rel!r} (manifest "
                    f"{want[:12]}…, got {got[:12]}…)")
        provenance = manifest.get("provenance")
        if provenance is not None:
            err = _provenance_error(provenance)
            if err:
                raise RepoCorruptError(
                    name, version, f"invalid provenance stamp — {err}")
        return ModelVersion(
            name=name, version=version, path=vdir,
            kind=manifest.get("kind", "bundle"),
            created=float(manifest.get("created", 0.0)),
            digests=dict(files), notes=manifest.get("notes", ""),
            provenance=provenance)

    def load(self, name: str, version: int | None = None
             ) -> tuple[Any, ModelVersion]:
        """Verify then deserialize one version; returns
        ``(model, ModelVersion)``. Verification happens BEFORE any
        deserialization — a corrupt artifact is refused with a typed
        error, it never reaches pickle/flax (where a truncated file
        would surface as an arbitrary exception mid-parse)."""
        info = self.verify(name, version)
        _faults().hit("load_failure", model=name)
        if info.kind == "bundle":
            from mmlspark_tpu.data.downloader import load_bundle_file
            model = load_bundle_file(os.path.join(info.path, BUNDLE_FILE))
        elif info.kind == "stage":
            from mmlspark_tpu.core.stage import PipelineStage
            model = PipelineStage.load(os.path.join(info.path, STAGE_DIR))
        else:
            raise RepoCorruptError(name, info.version,
                                   f"unknown artifact kind {info.kind!r}")
        return model, info

    # -- housekeeping --

    def prune(self, name: str, keep: int = 3) -> list[int]:
        """Delete all but the newest ``keep`` versions (the CURRENT
        version is always kept); returns the pruned version numbers."""
        if keep < 1:
            raise ValueError(f"keep must be >= 1: {keep}")
        with self._lock:
            versions = self.versions(name)
            current = self.current_version(name) if versions else None
            doomed = [v for v in versions[:-keep] if v != current]
            for v in doomed:
                shutil.rmtree(self._version_dir(name, v),
                              ignore_errors=True)
        return doomed

    def describe(self) -> dict:
        """JSON-safe repo inventory (the CLI's startup line)."""
        out = {}
        for name in self.models():
            out[name] = {"versions": self.versions(name),
                         "current": self.current_version(name)}
        return out

"""Host/device environment utilities."""

from mmlspark_tpu.utils.env import (
    device_count,
    device_kind,
    get_devices,
    local_device_count,
    on_tpu,
)

__all__ = [
    "get_devices",
    "device_count",
    "local_device_count",
    "device_kind",
    "on_tpu",
]

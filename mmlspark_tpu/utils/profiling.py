"""Profiler hooks: device-level traces to complement the Timer stage.

The reference's observability is wall-clock logging (Timer stage,
pipeline-stages/src/main/scala/Timer.scala:54-123 — mirrored by
stages/utility.Timer); on TPU the interesting time is *inside* the
compiled program, so these helpers expose the JAX/XLA profiler:

    from mmlspark_tpu.utils.profiling import trace, annotate

    with trace("/tmp/profile"):            # viewable in XProf/Perfetto
        with annotate("score-batch"):
            model.transform(table)

Traces capture per-op device timelines (MXU occupancy, HBM stalls, ICI
collectives) — the data behind every PERF_NOTES round.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Iterator


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[str]:
    """Capture a device trace for the enclosed block into ``log_dir``."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir,
                            create_perfetto_link=create_perfetto_link):
        yield log_dir


def annotate(name: str) -> Any:
    """Named span inside a trace (shows on the host timeline and groups
    the device ops dispatched under it)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def start_server(port: int = 9999) -> Any:
    """Live profiling endpoint for XProf's capture button."""
    import jax
    return jax.profiler.start_server(port)

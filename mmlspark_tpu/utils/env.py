"""Device/topology discovery — the accelerator-environment glue.

The reference discovers accelerators by shelling out to ``nvidia-smi -L``
(reference: core/env/src/main/scala/EnvironmentUtils.scala:20-50); the
TPU-native equivalent is JAX's device API, which also covers multi-host
process topology (``jax.process_index``) for the distributed backend.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence


def get_devices(backend: str | None = None) -> Sequence[Any]:
    import jax
    return jax.devices(backend) if backend else jax.devices()


def device_count() -> int:
    import jax
    return jax.device_count()


def local_device_count() -> int:
    import jax
    return jax.local_device_count()


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def device_kind() -> str:
    devs = get_devices()
    return devs[0].device_kind if devs else "none"


def on_tpu() -> bool:
    import jax
    return jax.default_backend() == "tpu"


def default_matmul_dtype():
    """bfloat16 on TPU (MXU-native), float32 elsewhere."""
    import jax.numpy as jnp
    return jnp.bfloat16 if on_tpu() else jnp.float32


def distributed_init(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Join the multi-host training world.

    The multi-node analog of the reference's hostfile-based MPI launcher
    stub (reference: cntk-train/src/main/scala/CommandBuilders.scala:95-117,
    never wired in): after this call ``jax.devices()`` is global across all
    hosts, so the same Mesh/pjit code spans slices (ICI within a slice, DCN
    between). On TPU pods all arguments are auto-discovered from the
    environment; pass them explicitly for CPU/GPU clusters.

    Arguments left as ``None`` fall back to the ``MMLSPARK_TPU_COORDINATOR``
    / ``MMLSPARK_TPU_NUM_PROCESSES`` / ``MMLSPARK_TPU_PROCESS_ID``
    environment variables, which is how ``mmlspark_tpu.tools.launch`` wires
    the worker processes it spawns; with neither args nor env set, JAX's
    own TPU-pod auto-discovery applies.
    """
    import os

    import jax
    if coordinator_address is None:
        coordinator_address = os.environ.get("MMLSPARK_TPU_COORDINATOR")
    if num_processes is None:
        num_processes = _env_int("MMLSPARK_TPU_NUM_PROCESSES")
    if process_id is None:
        process_id = _env_int("MMLSPARK_TPU_PROCESS_ID")
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def _env_int(name: str) -> int | None:
    import os
    raw = os.environ.get(name)
    return int(raw) if raw not in (None, "") else None


def topology_summary() -> dict[str, Any]:
    """One-call environment report (the GPUCount/nvidia-smi analog)."""
    import jax
    devs = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_count": len(devs),
        "local_device_count": jax.local_device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "device_kind": devs[0].device_kind if devs else "none",
        "platform": devs[0].platform if devs else "none",
    }

"""Device/topology discovery — the accelerator-environment glue.

The reference discovers accelerators by shelling out to ``nvidia-smi -L``
(reference: core/env/src/main/scala/EnvironmentUtils.scala:20-50); the
TPU-native equivalent is JAX's device API, which also covers multi-host
process topology (``jax.process_index``) for the distributed backend.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence


def get_devices(backend: str | None = None) -> Sequence[Any]:
    import jax
    return jax.devices(backend) if backend else jax.devices()


def device_count() -> int:
    import jax
    return jax.device_count()


def local_device_count() -> int:
    import jax
    return jax.local_device_count()


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def device_kind() -> str:
    devs = get_devices()
    return devs[0].device_kind if devs else "none"


def on_tpu() -> bool:
    import jax
    return jax.default_backend() == "tpu"


def default_matmul_dtype():
    """bfloat16 on TPU (MXU-native), float32 elsewhere."""
    import jax.numpy as jnp
    return jnp.bfloat16 if on_tpu() else jnp.float32

"""The distributed training loop — mesh-sharded jit steps, no external process.

Where the reference writes the dataset to a text file and shells out to
``mpiexec -n <gpus> cntk ... parallelTrain=true`` for 1-bit-SGD MPI
all-reduce (reference: cntk-train/src/main/scala/CNTKLearner.scala:140-151,
CommandBuilders.scala:79-93), this trains in-process:

* a ``Mesh`` over the devices (``dp`` axis = the MPI-ring analog),
* batch arrays sharded ``P(('dp','fsdp'))``, params replicated (or sharded
  over ``fsdp``/``tp`` for large models),
* the loss is a mean over the *global* batch, so XLA inserts the gradient
  ``psum`` over ICI automatically — the collectives ride the compiled step,
* optimizer = any optax transformation; state is a pure pytree, so
  checkpoint/resume is just (de)serializing it.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, Iterator

import numpy as np

from mmlspark_tpu.core import plan as plan_lib
from mmlspark_tpu.core.logging_utils import get_logger, timed
from mmlspark_tpu.obs import flight as _obs_flight
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.anomaly import NonFiniteSentinel, StragglerDetector
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import span as _obs_span
from mmlspark_tpu.parallel import mesh as mesh_lib
from mmlspark_tpu.train import preprocess as preprocess_lib

_log = get_logger(__name__)


def _slow_step_detector(loop: str):
    """Lazy accessor for the per-fit slow-step detector
    (:class:`mmlspark_tpu.obs.slo.SlowStepDetector`): flags steps whose
    dispatch time exceeds 4× the rolling window median as
    ``train/slow_step`` events + a ``train.slow_steps`` counter — the
    per-step health signal of a training run (a preempted host, a
    straggling collective, a donation stall all surface here). Created
    on first use so a fit with the tracer off never touches the
    registry; call sites gate on ``obs.runtime._enabled``."""
    box: dict = {}

    def get():
        det = box.get("det")
        if det is None:
            from mmlspark_tpu.obs.slo import SlowStepDetector
            det = box["det"] = SlowStepDetector(loop=loop)
        return det

    return get


@dataclasses.dataclass
class TrainConfig:
    batch_size: int = 128
    epochs: int = 1
    learning_rate: float = 1e-3
    optimizer: str = "adam"          # adam | sgd | momentum | adamw
    weight_decay: float = 0.0
    momentum: float = 0.9
    loss: str = "softmax_xent"       # softmax_xent | sigmoid_xent | mse
    # master-free low-precision training: cast params (and hence the
    # optimizer moments, which inherit leaf dtypes) to this dtype at init.
    # "bfloat16" halves param/moment HBM traffic per step — standard for
    # fine-tuning with SGD/momentum; avoid with adam (its second-moment
    # statistics need f32). None = float32 params (default)
    param_dtype: str | None = None
    # weight on sown "moe_aux" load-balance losses (MoE models); modules
    # that sow nothing are unaffected
    moe_aux_weight: float = 0.01
    seed: int = 0
    mesh_spec: Any = None            # MeshSpec | dict | None (dp over all)
    donate_state: bool = True
    log_every: int = 50
    # asynchronous input pipeline (train/input.py): batch assembly runs on
    # a background thread and the device commit is issued up to this many
    # batches ahead of consumption, so steady-state step wall-clock is
    # max(H2D, compute) instead of the sum; HBM held by in-flight batches
    # is bounded by the depth. 0 = fully synchronous (assemble + commit
    # inline in the step loop — the pre-round-7 behavior). Numerics are
    # bit-identical at every depth: the same host batches commit to the
    # same shardings in the same order
    prefetch_depth: int = 2
    # on-device scale applied after the f32 cast of uint8 inputs: uint8
    # image batches ship thin (¼ the H2D bytes of f32 — the round-2
    # inference convention applied to training) and cast/normalize INSIDE
    # the jitted step. The default maps raw bytes to [0, 1]; float inputs
    # are never touched
    input_scale: float = 1.0 / 255.0
    # on-device train preprocessing (train/preprocess.py): a
    # DevicePreprocess spec (or its plain-dict form) whose geometry
    # (source crop + bilinear resize), normalization, and stochastic
    # augmentation (pad-crop/flips/brightness/contrast) fuse INTO the
    # jitted step — one program, zero extra dispatches, thin uint8 on
    # the wire. Stochastic draws fold from the CHECKPOINTED global step,
    # so prefetch depth, host count, and resume all replay the identical
    # augmentation stream. None = the plain uint8 cast convention above
    preprocess: Any = None
    # multi-host fit_stream: local batches buffered per cross-process
    # liveness exchange. 1 = a host-side barrier every step (the
    # conservative round-3 behavior); larger values amortize it over up to
    # N device steps at the cost of buffering N local batches host-side.
    # Short processes pad the block with zero-weight filler, so step
    # counts are identical for any value
    liveness_sync_every: int = 8
    # multi-host fit_arrays: unequal per-process shard lengths normally
    # pad shorter shards with zero-weight rows (exact training — padded
    # rows contribute nothing); True restores the loud error instead
    strict_shards: bool = False
    # non-finite loss sentinel (obs/anomaly.py), checked on the SAME
    # one-step-lagged loss fetches the history already pays for (no new
    # host sync): "raise" (default) dies AT the divergence with a typed
    # NonFiniteLossError — and a flight-recorder dump when that is
    # enabled — "event" records train/nonfinite + a counter and
    # continues, "off" disables the check entirely
    nonfinite_loss: str = "raise"
    # mid-training checkpoint/resume (beyond-reference capability; SURVEY §5)
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0        # global steps between saves; 0 = end only
    max_to_keep: int = 3
    resume: bool = True              # restore latest checkpoint if present


def make_optimizer(cfg: TrainConfig):
    import optax
    if cfg.optimizer == "adam":
        return optax.adam(cfg.learning_rate)
    if cfg.optimizer == "adamw":
        return optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay)
    if cfg.optimizer == "sgd":
        return optax.sgd(cfg.learning_rate)
    if cfg.optimizer == "momentum":
        return optax.sgd(cfg.learning_rate, momentum=cfg.momentum)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")


def _row_reduce(per, token_mask, jnp):
    """[B, ...] per-position losses → [B] per-example.

    With a ``token_mask`` ([B, L]): masked mean — the mask must match the
    loss grid's leading dims exactly and broadcasts over any trailing
    (class) axes, so a per-token multi-label head ([B, L, K]) masks pad
    positions across all K classes. A mask that tiles neither way is a
    loud error, never a silent plain mean."""
    if token_mask is not None:
        if token_mask.shape == per.shape[:token_mask.ndim]:
            tm = token_mask.reshape(
                token_mask.shape + (1,) * (per.ndim - token_mask.ndim))
            tm = jnp.broadcast_to(tm, per.shape).astype(per.dtype)
        else:
            raise ValueError(
                f"token_mask shape {tuple(token_mask.shape)} does not "
                f"tile per-position loss shape {tuple(per.shape)}")
        per = (per * tm).reshape(per.shape[0], -1)
        tm = tm.reshape(per.shape)
        return per.sum(axis=1) / jnp.maximum(tm.sum(axis=1), 1.0)
    return per.reshape(per.shape[0], -1).mean(axis=1)


def make_loss(kind: str) -> Callable:
    """Per-example loss [B]; callers take a plain or mask-weighted mean
    (mask-weighting is how the padded tail batch trains without bias).

    ``token_mask`` ([B, L] 0/1, optional): per-token tasks reduce over L
    with a masked mean, so intra-row pad positions neither dilute the
    real-token loss nor push the model to predict tag 0 on padding
    (advisor round 4). The train step derives it from the module's
    ``pad_token_id`` when the input is a token matrix."""
    import jax.numpy as jnp
    import optax

    if kind == "softmax_xent":
        def loss(logits, labels, token_mask=None):
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels.astype(jnp.int32))
            # per-token tasks (logits [B, L, K], labels [B, L]) reduce to
            # one loss per example, like the other loss kinds — the masked
            # step weights rows by a [B] vector, so [B, L] would broadcast
            # wrongly (or only by luck when L == B)
            if per.ndim > 1:
                return _row_reduce(per, token_mask, jnp)
            return per
    elif kind == "sigmoid_xent":
        def loss(logits, labels, token_mask=None):
            z = logits
            if z.ndim > labels.ndim and z.shape[-1] == 1:
                z = z.squeeze(-1)  # binary head [B,1] vs labels [B]
            per = optax.sigmoid_binary_cross_entropy(
                z, labels.astype(z.dtype))
            # multi-label [B,K] / per-token: one loss per example
            if per.ndim > 1:
                return _row_reduce(per, token_mask, jnp)
            return per
    elif kind == "mse":
        def loss(logits, labels, token_mask=None):
            pred = logits.squeeze(-1) if logits.ndim > labels.ndim else logits
            per = (pred - labels.astype(pred.dtype)) ** 2
            # multi-target regression / per-token: one loss per example
            if per.ndim > 1:
                return _row_reduce(per, token_mask, jnp)
            return per
    else:
        raise ValueError(f"unknown loss {kind!r}")
    return loss


# THE 1-device fast-path criterion, shared with the elastic reshard
# targets (parallel/mesh.state_shardings): make_train_step's plain-jit
# path, Trainer.data_target's commit target, and reshard placement must
# always agree, or batches committed with a NamedSharding would feed a
# plain-jit program (or vice versa)
single_device = mesh_lib.single_device


def resolve_mesh_hooks(module: Any, mesh: Any) -> dict:
    """Ask the module how it uses the mesh beyond dp/fsdp/tp.

    Model families implement ``mesh_hooks(mesh) -> dict`` with keys:

    * ``apply_kwargs`` — extra kwargs for ``module.apply`` that activate a
      parallel execution path with the SAME params (e.g. a ring-attention
      ``attention_fn`` for ``sp``, an expert-parallel ``moe_fn`` for
      ``ep``, a ``pipeline_mesh`` for ``pp``),
    * ``param_rules`` — ``callable(path, leaf) -> PartitionSpec | None``
      placing structurally special params
      (:func:`mmlspark_tpu.parallel.mesh.param_shardings`),
    * ``handled`` — the set of extra mesh axes those kwargs actually use.

    This is how ``Trainer(module, mesh_spec={'ep': 2})`` *just works* —
    the one-flag UX of the reference's ``parallelTrain=true``
    (reference: cntk-train/src/main/scala/CommandBuilders.scala:79-93),
    generalized to six mesh axes.
    """
    hooks = {"apply_kwargs": {}, "param_rules": None, "handled": set()}
    if hasattr(module, "mesh_hooks"):
        got = module.mesh_hooks(mesh) or {}
        hooks["apply_kwargs"] = dict(got.get("apply_kwargs", {}))
        hooks["param_rules"] = got.get("param_rules")
        hooks["handled"] = set(got.get("handled", ()))
    return hooks


_EXTRA_AXES = ("sp", "pp", "ep")  # beyond the always-used dp/fsdp/tp


def check_mesh_axes_used(module: Any, mesh: Any, handled: set) -> None:
    """Refuse meshes with axes the training step would silently waste
    (round-4 verdict: an unhandled ``pp=2`` replicated all work over half
    the devices with no warning)."""
    unused = [a for a in _EXTRA_AXES if mesh.shape.get(a, 1) > 1
              and a not in handled]
    if unused:
        raise ValueError(
            f"mesh axes {unused} have extent > 1 but "
            f"{type(module).__name__} does not use them — training would "
            "silently replicate all work over those devices. Use a module "
            "that implements mesh_hooks for these axes (TransformerTagger:"
            " sp via ring attention, ep via moe_experts>0; ViT: pp via "
            "pipelined encoder blocks), or drop the axes from mesh_spec.")


def make_train_step(module: Any, cfg: TrainConfig, mesh: Any):
    """Build (init_state, step, step_masked) for a flax module on a mesh.

    ``step(state, x, y) -> (state, metrics)`` is one jit-compiled program:
    forward (bf16 on MXU), backward, global-mean gradients (XLA psum over
    ``dp``/``fsdp`` ICI rings), optimizer update. ``step_masked`` takes an
    extra per-example weight vector ``w`` (0/1) and computes the weighted
    mean — how the zero-padded tail batch trains without bias.

    Extra mesh axes (``sp``/``pp``/``ep``) activate through the module's
    ``mesh_hooks`` (see :func:`resolve_mesh_hooks`); a mesh axis nothing
    uses raises instead of silently replicating work.
    """
    import jax
    import jax.numpy as jnp
    import optax

    tx = make_optimizer(cfg)
    loss_fn = make_loss(cfg.loss)
    pp = preprocess_lib.resolve(cfg.preprocess)
    hooks = resolve_mesh_hooks(module, mesh)
    check_mesh_axes_used(module, mesh, hooks["handled"])
    apply_kwargs = hooks["apply_kwargs"]
    # single-device fast path: plain placement + plain jit. NamedSharding
    # transfers/fetches take a multi-round-trip path through remote-device
    # tunnels (~4.5 ms/step measured on the ViT bench config, PERF_NOTES
    # round 4) — the same choice models/jax_model.py makes for inference
    dev0 = single_device(mesh)
    single = dev0 is not None
    repl = dev0 if single else mesh_lib.replicated(mesh)

    def init_state(input_spec: tuple) -> dict:
        from jax.sharding import NamedSharding

        rng = jax.random.PRNGKey(cfg.seed)
        shape = tuple(input_spec)
        if pp is not None and len(shape) == 3:
            # the module sees POST-preprocess geometry: a thin-wire
            # 40x40 source trains a 32x32 model when the spec resizes
            shape = pp.out_shape(shape)
        dummy = jnp.zeros((1,) + shape, jnp.float32)
        params = module.init(rng, dummy)["params"]
        if cfg.param_dtype:
            dt = jnp.dtype(cfg.param_dtype)
            params = jax.tree_util.tree_map(
                lambda a: a.astype(dt) if jnp.issubdtype(
                    a.dtype, jnp.floating) else a, params)
        # fsdp > 1 → zero-style parameter sharding; optimizer moments
        # inherit the leaf shardings through eager zeros_like propagation.
        # module param_rules place structurally special leaves first
        # (e.g. MoE expert stacks over ep)
        params = jax.device_put(
            params, dev0 if single
            else mesh_lib.param_shardings(mesh, params,
                                          rules=hooks["param_rules"]))
        opt_state = tx.init(params)

        # scalar leaves optax creates itself (e.g. adam's step count) land
        # uncommitted on the default device; commit them replicated so the
        # WHOLE state tree has explicit mesh shardings — required for a
        # checkpoint restore to rebuild arrays every process can address
        # (a single-local-device scalar restores fine on one process but
        # is not a global array, and the multi-host step rejects it)
        def commit_leaf(leaf):
            if single:
                return jax.device_put(leaf, dev0)
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                return leaf  # inherited a mesh sharding already
            return jax.device_put(leaf, repl)

        opt_state = jax.tree_util.tree_map(commit_leaf, opt_state)
        return {"params": params, "opt_state": opt_state,
                "step": jax.device_put(jnp.zeros((), jnp.int32), repl)}

    def _update(state, loss, grads):
        updates, opt_state = tx.update(
            grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss}

    def _forward(params, x):
        """Apply with sown-intermediate capture: modules that sow auxiliary
        losses (e.g. the MoE load-balance term, models/sequence.py) train
        them through the standard Trainer instead of silently dropping
        them (flax discards sow() into an immutable collection)."""
        out, mut = module.apply({"params": params}, x, train=True,
                                mutable=["intermediates"], **apply_kwargs)
        from collections.abc import Mapping

        aux = jnp.zeros((), jnp.float32)
        inter = mut.get("intermediates", {})

        def walk(node):
            nonlocal aux
            if isinstance(node, Mapping):  # dict or flax FrozenDict
                for k, v in node.items():
                    if k == "moe_aux":
                        for leaf in jax.tree_util.tree_leaves(v):
                            aux = aux + jnp.mean(leaf)
                    else:
                        walk(v)

        walk(inter)
        return out, aux

    def _token_mask(x):
        """[B, L] 0/1 pad mask derived the same way the module derives its
        attention mask (pad_token_id) — per-token tasks then reduce over L
        with a masked mean instead of diluting real-token loss with
        padding (advisor round 4)."""
        pad_id = getattr(module, "pad_token_id", None)
        if (pad_id is not None and getattr(x, "ndim", 0) == 2
                and jnp.issubdtype(x.dtype, jnp.integer)):
            return (x.astype(jnp.int32) != pad_id).astype(jnp.float32)
        return None

    def _prep_x(x, step):
        # uint8 ships thin (¼ the H2D bytes) and casts/normalizes on
        # device — the round-2 inference convention, applied to training.
        # Token matrices are int32/int64 and pass through untouched.
        # With a DevicePreprocess spec, NHWC image batches additionally
        # replay geometry + stochastic augmentation in-step, keyed off
        # the (checkpointed) global step so every replay is bit-exact
        if pp is not None and getattr(x, "ndim", 0) == 4:
            key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), step)
            return preprocess_lib.apply(pp, key, x, cfg.input_scale)
        if x.dtype == jnp.uint8:
            return x.astype(jnp.float32) * cfg.input_scale
        return x

    def _step(state, x, y):
        def compute_loss(params):
            logits, aux = _forward(params, _prep_x(x, state["step"]))
            per = loss_fn(logits, y, token_mask=_token_mask(x))
            return per.mean() + cfg.moe_aux_weight * aux

        loss, grads = jax.value_and_grad(compute_loss)(state["params"])
        return _update(state, loss, grads)

    def _step_masked(state, x, y, w):
        # weighted global mean: zero-weight (padded) rows contribute nothing
        # to loss or gradients, so the tail batch trains exactly. The
        # clamped denominator makes an all-zero-weight batch (multi-host
        # filler between liveness syncs) an exact no-op instead of 0/0 NaN
        def compute_loss(params):
            logits, aux = _forward(params, _prep_x(x, state["step"]))
            per = loss_fn(logits, y, token_mask=_token_mask(x))
            # gate the aux term on the row weights too: an all-filler batch
            # must be an EXACT no-op, but routing statistics are computed
            # over the whole batch and would otherwise leak gate gradients
            # (advisor round 4)
            aux = aux * jnp.minimum(w.sum(), 1.0)
            return ((per * w).sum() / jnp.maximum(w.sum(), 1e-6)
                    + cfg.moe_aux_weight * aux)

        loss, grads = jax.value_and_grad(compute_loss)(state["params"])
        return _update(state, loss, grads)

    # state shardings are inferred from the committed arrays built by
    # init_state (replicated or fsdp-sharded per param_shardings); batch
    # shardings stay EXPLICIT so direct callers passing host numpy batches
    # still get dp-sharded data rather than silent replication. On a
    # 1-device mesh plain jit skips the sharding machinery entirely
    donate = (0,) if cfg.donate_state else ()
    if single:
        step = jax.jit(_step, donate_argnums=donate)
        step_masked = jax.jit(_step_masked, donate_argnums=donate)
    else:
        data = mesh_lib.batch_sharding(mesh)
        step = jax.jit(_step, in_shardings=(None, data, data),
                       donate_argnums=donate)
        step_masked = jax.jit(_step_masked,
                              in_shardings=(None, data, data, data),
                              donate_argnums=donate)
    return init_state, step, step_masked


def _batches(x: np.ndarray, y: np.ndarray, batch_size: int,
             seed: int, valid: np.ndarray | None = None) -> Iterator[tuple]:
    """Shuffled fixed-shape batches ``(bx, by, bw)``. The tail batch is
    zero-padded to ``batch_size`` with a 0/1 weight vector so no row is ever
    dropped (round-1/2 fix: ``drop_remainder`` silently lost up to
    ``batch_size-1`` rows per epoch) while XLA still sees one shape.

    ``valid`` (0/1 per row) marks rows that are themselves padding (the
    unequal-multi-host-shard case): they shuffle through the walk like any
    row but carry weight 0, so the batch count stays process-uniform while
    the padded rows train as exact no-ops."""
    n = len(x)
    order = np.random.default_rng(seed).permutation(n)
    weights = (np.ones(n, np.float32) if valid is None
               else np.asarray(valid, np.float32))
    for s in range(0, n, batch_size):
        idx = order[s:s + batch_size]
        if len(idx) == batch_size:
            yield x[idx], y[idx], weights[idx]
        else:
            pad = batch_size - len(idx)
            bx = np.concatenate([x[idx], np.zeros((pad,) + x.shape[1:],
                                                  x.dtype)])
            by = np.concatenate([y[idx], np.zeros((pad,) + y.shape[1:],
                                                  y.dtype)])
            bw = np.concatenate([weights[idx], np.zeros(pad, np.float32)])
            yield bx, by, bw


_SIG_BYTES = 256


def _sync_batch_signature(batch: Any) -> tuple | None:
    """All-gather this process's (x, y) tail shapes/dtypes; return the
    first non-empty peer signature. Keeps multi-host filler batches (and
    hence the compiled step program) identical on every process even when
    one process's stream is empty."""
    import json

    from jax.experimental import multihost_utils

    if batch is None:
        mine = np.zeros(_SIG_BYTES, np.uint8)
    else:
        bx, by, _ = batch
        enc = json.dumps({
            "xs": [int(d) for d in bx.shape[1:]], "xd": bx.dtype.str,
            "ys": [int(d) for d in by.shape[1:]], "yd": by.dtype.str,
        }).encode()
        if len(enc) > _SIG_BYTES:
            raise ValueError(f"batch signature too large: {enc!r}")
        mine = np.frombuffer(enc.ljust(_SIG_BYTES, b"\0"), np.uint8).copy()
    sigs = np.asarray(multihost_utils.process_allgather(mine))
    for row in sigs.reshape(-1, _SIG_BYTES):
        raw = bytes(row).rstrip(b"\0")
        if raw:
            d = json.loads(raw)
            return ((tuple(d["xs"]), np.dtype(d["xd"])),
                    (tuple(d["ys"]), np.dtype(d["yd"])))
    return None


def _rebatch(chunks: Any, batch_size: int) -> Iterator[tuple]:
    """Re-accumulate arbitrary-size (x, y) chunks into fixed-size batches
    ``(bx, by, bw)``; the final partial batch is zero-padded with a 0/1
    weight vector. Memory is bounded by one batch + one chunk."""
    buf_x: list[np.ndarray] = []
    buf_y: list[np.ndarray] = []
    have = 0
    for cx, cy in chunks:
        if len(cx) != len(cy):
            raise ValueError(f"chunk length mismatch: {len(cx)} vs {len(cy)}")
        buf_x.append(np.asarray(cx))
        buf_y.append(np.asarray(cy))
        have += len(cx)
        while have >= batch_size:
            x = np.concatenate(buf_x) if len(buf_x) > 1 else buf_x[0]
            y = np.concatenate(buf_y) if len(buf_y) > 1 else buf_y[0]
            yield (x[:batch_size], y[:batch_size],
                   np.ones(batch_size, np.float32))
            buf_x, buf_y = [x[batch_size:]], [y[batch_size:]]
            have -= batch_size
    if have:
        x = np.concatenate(buf_x) if len(buf_x) > 1 else buf_x[0]
        y = np.concatenate(buf_y) if len(buf_y) > 1 else buf_y[0]
        pad = batch_size - have
        bx = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        by = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
        bw = np.concatenate([np.ones(have, np.float32),
                             np.zeros(pad, np.float32)])
        yield bx, by, bw


class Trainer:
    """Minimal array-in training driver used by the learners and bench.

    Handles mesh creation, state init, epoch loops, and loss tracking. The
    estimator-level one-call API (featurize → train → scored model) builds
    on this in the train package's classifier/regressor stages.
    """

    def __init__(self, module: Any, cfg: TrainConfig | None = None,
                 mesh: Any = None):
        self.module = module
        self.cfg = cfg or TrainConfig()
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(
            self.cfg.mesh_spec)
        self.init_state, self.step, self.step_masked = make_train_step(
            module, self.cfg, self.mesh)
        self.state = None
        self.history: list[float] = []
        # per-step input-wait vs. step-time accounting for the last fit
        # (train/input.input_stats): input_bound_fraction, wait/step split,
        # committed_ahead_max — the honest answer to "was that run input-
        # bound or compute-bound?"
        self.input_stats: dict | None = None
        self._fingerprint: dict | None = None

    def data_target(self):
        """Where host batches commit: the bare device on a 1-device mesh
        (plain transfers — see make_train_step's fast path), else the
        dp-sharded NamedSharding. Shares the `single_device` predicate
        with make_train_step so the two can never disagree."""
        dev0 = single_device(self.mesh)
        return dev0 if dev0 is not None else mesh_lib.batch_sharding(
            self.mesh)

    def _checkpointer(self):
        if not self.cfg.checkpoint_dir:
            return None
        if getattr(self, "_ckpt", None) is None:
            from mmlspark_tpu.train.checkpoint import TrainCheckpointer
            self._ckpt = TrainCheckpointer(self.cfg.checkpoint_dir,
                                           self.cfg.max_to_keep)
        return self._ckpt

    def maybe_restore(self) -> int | None:
        """Resume from the latest checkpoint if configured; returns the
        restored global step or None."""
        ckpt = self._checkpointer()
        if ckpt is None or not self.cfg.resume:
            return None
        latest = ckpt.latest_step()
        if latest is None:
            return None
        # resume replays the first `resumed` batches as no-ops, which is only
        # correct if the schedule (dataset length, batch size, seed, epochs)
        # is identical to the run that wrote the checkpoint — validate it
        saved = ckpt.fingerprint()
        if (saved is not None and self._fingerprint is not None
                and saved != self._fingerprint):
            raise ValueError(
                "checkpoint schedule fingerprint mismatch: saved "
                f"{saved} vs current {self._fingerprint}; resuming would "
                "silently skip the wrong batches. Start a fresh "
                "checkpoint_dir (or set resume=False) to train with a "
                "changed dataset/batch_size/seed/epochs")
        # restores directly to each target leaf's sharding — the target
        # was built by init_state on THIS trainer's mesh, so a checkpoint
        # written on a different topology reshards on read (elastic
        # recovery). step=None takes the integrity-validated path: a torn
        # latest step falls back to the previous manifest step instead of
        # crashing the recovery (train/checkpoint_corrupt event)
        self.state = ckpt.restore(target=self.state)
        restored = int(np.asarray(self.state["step"]))
        _log.info(f"resumed from checkpoint step {restored} "
                  f"({self.cfg.checkpoint_dir})")
        return restored

    def save_checkpoint(self) -> int | None:
        ckpt = self._checkpointer()
        if ckpt is None:
            return None
        return ckpt.save(self.state, fingerprint=self._fingerprint)

    def rescale(self, mesh: Any = None, mesh_spec: Any = None) -> "Trainer":
        """Re-form the training step on a new mesh and reshard live state
        onto it — the in-process elastic path (surviving devices
        re-forming after a topology change; the cross-process path
        restores a checkpoint on the new topology instead).

        The step/step_masked programs are rebuilt for the new mesh and
        every state leaf is bit-preserved through
        :func:`mmlspark_tpu.train.checkpoint.reshard_state`, so the next
        ``fit_*`` call continues the schedule exactly where the old
        topology left it. The schedule fingerprint is unchanged — which
        also means the new data-parallel extent must keep the effective
        batch size identical (it must still divide the configured batch),
        or the resume-replay validation refuses loudly.
        """
        old_mesh = self.mesh
        new_mesh = mesh if mesh is not None else mesh_lib.make_mesh(
            mesh_spec if mesh_spec is not None else self.cfg.mesh_spec)
        self.init_state, self.step, self.step_masked = make_train_step(
            self.module, self.cfg, new_mesh)
        self.mesh = new_mesh
        if self.state is not None:
            from mmlspark_tpu.train.checkpoint import reshard_state
            hooks = resolve_mesh_hooks(self.module, new_mesh)
            self.state = reshard_state(self.state, old_mesh, new_mesh,
                                       rules=hooks["param_rules"])
        if _obs_rt._enabled:
            _obs_registry().counter("train.rescales").add()
        _log.info("rescaled trainer mesh %s -> %s",
                  dict(zip(old_mesh.axis_names, old_mesh.devices.shape)),
                  dict(zip(new_mesh.axis_names, new_mesh.devices.shape)))
        return self

    def _note_loss(self, value: float) -> None:
        """Record one logged loss: appended to ``self.history`` AND
        published to the windowed ``train.loss`` histogram
        (tracer-gated) — the eval series the service beacon exports to
        the supervisor, where the lifecycle ``EvalGate`` judges it
        (docs/lifecycle.md)."""
        self.history.append(value)
        if _obs_rt._enabled:
            _obs_registry().histogram("train.loss").observe(float(value))

    def fit_arrays(self, x: np.ndarray, y: np.ndarray) -> "Trainer":
        """Train on host arrays.

        Multi-host: each process passes only its own equal-length shard of
        the dataset (the per-host sharded input pipeline, SURVEY §5 — no
        shuffle engine; file-shard → host → HBM). Global batches are
        assembled from every process's local slice via
        ``jax.make_array_from_process_local_data``; ``cfg.batch_size`` is
        the GLOBAL batch size.
        """
        import jax

        cfg = self.cfg
        nproc = jax.process_count()
        valid: np.ndarray | None = None
        if nproc > 1:
            # every process must walk the same number of steps or the
            # gradient all-reduce deadlocks. Unequal shards pad up to the
            # longest with zero-weight rows (exact: padded rows shuffle
            # through the walk contributing nothing); strict_shards=True
            # restores the loud error for jobs where unequal shards can
            # only mean an upstream partitioning bug
            from jax.experimental import multihost_utils
            lens = np.asarray(multihost_utils.process_allgather(
                np.asarray(len(x), np.int64)))
            if len(set(lens.tolist())) != 1:
                if cfg.strict_shards:
                    raise ValueError(
                        "fit_arrays multi-host requires equal per-process "
                        f"shard lengths, got {lens.tolist()} (strict_shards"
                        "=True) — pad or trim the shards, or use fit_stream "
                        "(which reconciles unequal streams with filler "
                        "batches)")
                longest = int(lens.max())
                _log.warning(
                    "fit_arrays: unequal per-process shards %s — padding "
                    "to %d rows with zero-weight filler",
                    lens.tolist(), longest)
                pad = longest - len(x)
                valid = np.concatenate([np.ones(len(x), np.float32),
                                        np.zeros(pad, np.float32)])
                if pad:
                    x = np.concatenate(
                        [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
                    y = np.concatenate(
                        [y, np.zeros((pad,) + y.shape[1:], y.dtype)])
        # the batch must divide over the data axes AND split evenly across
        # processes (each contributes bs/nproc rows), so round down to a
        # multiple of lcm(dp, nproc)
        dp = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        import math as _math
        q = _math.lcm(dp, nproc)
        n_global = len(x) * nproc
        bs = (min(cfg.batch_size, n_global) // q) * q
        if bs == 0:
            raise ValueError(
                f"dataset of {n_global} rows (or batch_size "
                f"{cfg.batch_size}) is smaller than "
                f"lcm(data-parallel extent {dp}, processes {nproc}) = {q}")
        # each process walks its local shard with the same seed; the global
        # batch is the process-order concatenation of the local slices
        bs_local = bs // nproc
        # fingerprint the EFFECTIVE batch size: resuming on a mesh with a
        # different dp extent changes the rounded bs (and hence the batch
        # walk) even when cfg.batch_size is unchanged. sched=2 marks the
        # padded-tail batch walk (one more step per epoch than sched-1 runs)
        # param_dtype is part of the fingerprint: restoring an f32
        # checkpoint into bf16 targets (or vice versa) would silently
        # change precision mid-run instead of erroring loudly
        self._fingerprint = {"n_rows": int(n_global),
                             "batch_size": int(bs),
                             "seed": int(cfg.seed),
                             "epochs": int(cfg.epochs),
                             "param_dtype": cfg.param_dtype or "float32",
                             "sched": 2}
        if cfg.preprocess is not None:
            # resuming under a CHANGED preprocess spec would silently
            # replay different pixels into the remaining steps
            self._fingerprint["preprocess"] = preprocess_lib.resolve(
                cfg.preprocess).fingerprint()
        resumed = 0
        if self.state is None:
            self.state = self.init_state(x.shape[1:])
            resumed = self.maybe_restore() or 0
        data = self.data_target()
        ckpt = self._checkpointer()
        # resume completes the REMAINDER of the configured schedule: the
        # first `resumed` (already-trained) steps of the epoch/batch walk are
        # replayed as no-ops so batch order stays deterministic. The resumed
        # prefix is skipped in the PRODUCER, before assembly/commit — a
        # replayed batch never crosses the link
        from mmlspark_tpu.train.input import DeviceLoader, input_stats

        if nproc > 1:
            def commit(arr):
                # local slice → its block of the globally-sharded array
                # (multi-host assembly has no single-transfer seam to
                # route through — bytes are accounted by the loader)
                return jax.make_array_from_process_local_data(data, arr)
        else:
            def commit(arr):
                # through the planner's upload seam: train-path H2D
                # transfers share the crossing/byte counters (and
                # count_crossings patches) with the pipeline executor
                return plan_lib.train_commit(arr, data)

        total_steps = cfg.epochs * (-(-len(x) // bs_local))

        def host_batches():
            gs = 0
            for epoch in range(cfg.epochs):
                for i, batch in enumerate(
                        _batches(x, y, bs_local, cfg.seed + epoch, valid)):
                    gs += 1
                    if gs <= resumed:
                        continue
                    yield gs, i, batch

        def commit_batch(item):
            gs, i, (bx, by, bw) = item
            return gs, i, (commit(bx), commit(by), commit(bw))

        # one-step-lagged loss fetch: resolving the PREVIOUS log point's
        # device scalar never stalls the in-flight prefetch window (the
        # inline float() was a host sync mid-pipeline every log_every
        # steps). The non-finite sentinel rides these exact fetches
        pending = None  # (global step, device loss scalar)
        sentinel = NonFiniteSentinel("fit_arrays", cfg.nonfinite_loss)
        loader = DeviceLoader(host_batches(), commit_batch,
                              depth=cfg.prefetch_depth, name="fit_arrays")
        slow_steps = _slow_step_detector("fit_arrays")
        hb = "train/fit_arrays"  # flight-recorder heartbeat: a step loop
        #                          that stops stepping is a hang
        if _obs_flight._rec is not None:
            _obs_flight._rec.arm(hb)
        t_loop = time.perf_counter()
        try:
            with timed(f"Trainer[{type(self.module).__name__}]", _log,
                       len(x)):
                for gs, i, (dx, dy, dw) in loader:
                    # the span times step DISPATCH (async issue), not
                    # device compute — the honest host-side number; the
                    # wait surfaces in the loader's wait span instead
                    t_step = time.perf_counter() if _obs_rt._enabled \
                        else None
                    with _obs_span("train/step", "train"):
                        self.state, metrics = self.step_masked(
                            self.state, dx, dy, dw)
                    if _obs_flight._rec is not None:
                        _obs_flight._rec.beat(hb)
                    if _obs_rt._enabled:
                        _obs_registry().counter("train.steps").add()
                        if t_step is not None:
                            slow_steps().observe(
                                (time.perf_counter() - t_step) * 1e3)
                    if i % cfg.log_every == 0:
                        if pending is not None:
                            self._note_loss(sentinel.check(
                                pending[0], float(pending[1])))  # lint-jax: allow(JX105) — one-step-lagged fetch
                        pending = (gs, metrics["loss"])
                    if (ckpt is not None and cfg.checkpoint_every > 0
                            and gs % cfg.checkpoint_every == 0):
                        self.save_checkpoint()
            if pending is not None:
                self._note_loss(sentinel.check(pending[0],
                                               float(pending[1])))
                pending = None
        except BaseException as e:
            # the post-mortem happens AT the failure point, before any
            # caller can swallow the exception (obs/flight.py)
            _obs_flight.on_crash(e, context="Trainer.fit_arrays")
            raise
        finally:
            loader.close()
            if _obs_flight._rec is not None:
                _obs_flight._rec.disarm(hb)
        self.input_stats = input_stats(loader, time.perf_counter() - t_loop)
        if ckpt is not None and total_steps > resumed:
            self.save_checkpoint()
        return self

    def fit_stream(self, source: Any, input_spec: tuple | None = None
                   ) -> "Trainer":
        """Train from a stream of ``(x_chunk, y_chunk)`` host arrays without
        ever materializing the dataset (bounded-memory ingest; reference
        streaming reader: readers/src/main/scala/ImageReader.scala:85-98).

        ``source`` is an iterable of chunks, or a zero-arg callable
        returning a fresh iterator (required when ``cfg.epochs > 1``).
        Chunks may be any size: rows are re-accumulated into fixed
        ``cfg.batch_size`` global batches (one XLA program), with the final
        partial batch padded + masked. Multi-host: each process streams its
        own shard, exactly as in :meth:`fit_arrays`.
        """
        import jax

        cfg = self.cfg
        nproc = jax.process_count()
        dp = self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
        import math as _math
        q = _math.lcm(dp, nproc)
        bs = (cfg.batch_size // q) * q
        if bs == 0:
            raise ValueError(
                f"batch_size {cfg.batch_size} smaller than lcm("
                f"data-parallel extent {dp}, processes {nproc}) = {q}")
        bs_local = bs // nproc

        def epoch_iter():
            it = source() if callable(source) else source
            return _rebatch(it, bs_local)

        if cfg.epochs > 1 and not callable(source):
            raise ValueError(
                "epochs > 1 needs a callable source (a fresh iterator per "
                "epoch); a plain iterator is exhausted after one pass")

        data = self.data_target()
        if nproc > 1:
            def commit(arr):
                return jax.make_array_from_process_local_data(data, arr)
        else:
            def commit(arr):
                return plan_lib.train_commit(arr, data)  # counted seam

        # streams have no stable row count; fingerprint only the schedule
        # shape that must match for a resume to replay correctly
        self._fingerprint = {"stream": True, "batch_size": int(bs),
                             "seed": int(cfg.seed),
                             "epochs": int(cfg.epochs),
                             "param_dtype": cfg.param_dtype or "float32",
                             "sched": 2}
        if cfg.preprocess is not None:
            self._fingerprint["preprocess"] = preprocess_lib.resolve(
                cfg.preprocess).fingerprint()
        ckpt = self._checkpointer()
        # producer-side progress, read by the consumer once the loader is
        # drained (the worker has exited by then): walked steps include the
        # resumed prefix, rows count only real (non-filler) examples
        prog = {"steps": 0, "rows": 0, "resumed": 0}
        box: dict = {"loader": None}

        from mmlspark_tpu.train.input import DeviceLoader, input_stats

        def ensure_state(bx) -> None:
            # runs on the producer thread BEFORE the first batch is
            # yielded — the consumer is still blocked on the queue, so
            # state init / checkpoint restore never overlaps step dispatch
            if self.state is None:
                spec = tuple(input_spec or bx.shape[1:])
                self.state = self.init_state(spec)
                prog["resumed"] = self.maybe_restore() or 0

        def fence() -> None:
            # multi-host: every cross-process exchange must interleave
            # with step dispatch in the same order on every process —
            # drain the in-flight window before issuing the collective
            # (docs/training_input.md, "lockstep rules")
            if box["loader"] is not None:
                box["loader"].drain_barrier()

        def dummy_batch(shapes: tuple | None) -> tuple:
            # zero-weight filler keeping cross-process collectives aligned
            # when this process's shard ran dry before its peers'
            if shapes is not None:
                (xs, xd), (ys, yd) = shapes
            elif input_spec is not None:
                (xs, xd), (ys, yd) = ((tuple(input_spec), np.float32),
                                      ((), np.int64))
            else:
                raise ValueError(
                    "this process's stream yielded no data and no "
                    "input_spec was given; cannot synthesize filler "
                    "batches for the multi-host schedule")
            return (np.zeros((bs_local,) + xs, xd),
                    np.zeros((bs_local,) + ys, yd),
                    np.zeros(bs_local, np.float32))

        import itertools as _itertools
        sync_n = max(int(cfg.liveness_sync_every), 1)

        def host_batches():
            # chunk pull (→ image decode in streaming sources) + rebatch +
            # filler/liveness reconciliation, all on the producer thread —
            # with prefetch_depth > 0 the whole input side overlaps step
            # compute. Filler batches and the signature sync flow through
            # unchanged, so the multi-host step walk is identical to the
            # synchronous path
            shapes: tuple | None = None  # (x tail shape/dtype, y tail/dt)
            sig_synced = False
            gs = 0
            for epoch in range(cfg.epochs):
                it = iter(epoch_iter())
                if nproc > 1 and not sig_synced:
                    # exchange batch signatures once (symmetric across
                    # processes): a process whose shard is empty adopts its
                    # peers' shapes/dtypes for filler batches, so every
                    # process compiles the identical step program
                    fence()
                    first = next(it, None)
                    shapes = _sync_batch_signature(first) or shapes
                    sig_synced = True
                    if first is not None:
                        it = _itertools.chain([first], it)
                while True:
                    if nproc > 1:
                        # streams rarely shard into equal batch counts per
                        # process, and a process that runs dry would leave
                        # its peers deadlocked inside the step's
                        # collectives. Buffer up to sync_n local batches,
                        # exchange counts ONCE per block (the host-side
                        # barrier amortizes over the whole block instead
                        # of serializing every step — advisor round 3),
                        # and let short processes pad with zero-weight
                        # filler up to the block's max count. Step counts
                        # are exact: the longest stream sets the walk.
                        # The liveness payload carries (count, mean step
                        # ms): the straggler exchange RIDES the same
                        # fenced collective — no new exchange site, and
                        # the schedule is identical on every process
                        # whether or not its tracer is enabled
                        block = list(_itertools.islice(it, sync_n))
                        # the fence + allgather is the one seam every
                        # process crosses at the same real instant — the
                        # span is the fleet plane's skew-correction and
                        # flow-stitch anchor (obs/fleet.FENCE_SPAN_NAMES)
                        with _obs_span("train/liveness_sync", "train"):
                            fence()
                            from jax.experimental import multihost_utils
                            payload = np.asarray(
                                [float(len(block)),
                                 straggler.local_mean_ms()], np.float64)
                            gathered = np.asarray(
                                multihost_utils.process_allgather(
                                    payload)).reshape(-1, 2)
                        block_steps = int(gathered[:, 0].max())
                        if _obs_rt._enabled:
                            straggler.ingest(gathered[:, 1],
                                             jax.process_index())
                        if block_steps == 0:
                            break
                        block += [None] * (block_steps - len(block))
                    else:
                        nxt = next(it, None)
                        if nxt is None:
                            break
                        block = [nxt]
                    for batch in block:
                        if batch is None:
                            batch = dummy_batch(shapes)
                        bx, by, bw = batch
                        shapes = ((bx.shape[1:], bx.dtype),
                                  (by.shape[1:], by.dtype))
                        ensure_state(bx)
                        gs += 1
                        prog["steps"] = gs
                        if gs <= prog["resumed"]:
                            continue
                        prog["rows"] += int(bw.sum())
                        yield gs, batch

        def commit_batch(item):
            gs, (bx, by, bw) = item
            return gs, (commit(bx), commit(by), commit(bw))

        pending = None  # (step, loss) one-step-lagged fetch (fit_arrays)
        sentinel = NonFiniteSentinel("fit_stream", cfg.nonfinite_loss)
        # created BEFORE the loader: its worker starts pulling
        # host_batches immediately, and that closure reads `straggler`
        straggler = StragglerDetector("fit_stream")
        loader = DeviceLoader(host_batches(), commit_batch,
                              depth=cfg.prefetch_depth, name="fit_stream")
        box["loader"] = loader
        slow_steps = _slow_step_detector("fit_stream")
        hb = "train/fit_stream"
        if _obs_flight._rec is not None:
            _obs_flight._rec.arm(hb)
        t_loop = time.perf_counter()
        try:
            with timed(f"Trainer[{type(self.module).__name__}:stream]",
                       _log):
                for gs, (dx, dy, dw) in loader:
                    t_step = time.perf_counter() if _obs_rt._enabled \
                        else None
                    with _obs_span("train/step", "train"):
                        self.state, metrics = self.step_masked(
                            self.state, dx, dy, dw)
                    if _obs_flight._rec is not None:
                        _obs_flight._rec.beat(hb)
                    if _obs_rt._enabled:
                        _obs_registry().counter("train.steps").add()
                        if t_step is not None:
                            dur_ms = (time.perf_counter() - t_step) * 1e3
                            slow_steps().observe(dur_ms)
                            straggler.observe(dur_ms)
                    if (gs - 1) % cfg.log_every == 0:
                        if pending is not None:
                            self._note_loss(sentinel.check(
                                pending[0], float(pending[1])))  # lint-jax: allow(JX105) — one-step-lagged fetch
                        pending = (gs, metrics["loss"])
                    if (ckpt is not None and cfg.checkpoint_every > 0
                            and gs % cfg.checkpoint_every == 0):
                        self.save_checkpoint()
                    # AFTER the checkpoint: save_checkpoint's
                    # sync_global_devices is itself a cross-process
                    # collective, so the producer's drain_barrier must
                    # hold until it completes — releasing it at step
                    # dispatch would let the liveness allgather race the
                    # checkpoint barrier across processes
                    loader.note_dispatched()
            if pending is not None:
                self._note_loss(sentinel.check(pending[0],
                                               float(pending[1])))
                pending = None
        except BaseException as e:
            _obs_flight.on_crash(e, context="Trainer.fit_stream")
            raise
        finally:
            loader.close()
            if _obs_flight._rec is not None:
                _obs_flight._rec.disarm(hb)
        self.input_stats = input_stats(loader, time.perf_counter() - t_loop)
        if prog["steps"] == 0:
            raise ValueError(
                "fit_stream: the stream yielded no data (empty source or "
                "mistyped path?)")
        _log.info("fit_stream: %d rows in %d steps", prog["rows"],
                  prog["steps"])
        if ckpt is not None and prog["steps"] > prog["resumed"]:
            self.save_checkpoint()
        return self

    @property
    def params(self):
        return self.state["params"]

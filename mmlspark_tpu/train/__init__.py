"""Training layer: in-process distributed train loops, one-call trainers,
and evaluation.

Replaces the reference's out-of-process ``mpiexec cntk`` training
(reference: cntk-train/src/main/scala/CNTKLearner.scala:52-162) with
jit-compiled steps sharded over a device mesh.
"""

from mmlspark_tpu.train.loop import TrainConfig, Trainer, make_train_step

__all__ = ["TrainConfig", "Trainer", "make_train_step"]

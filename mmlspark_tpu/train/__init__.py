"""Training layer: in-process distributed train loops, one-call trainers,
checkpoint/resume, and the JaxLearner estimator.

Replaces the reference's out-of-process ``mpiexec cntk`` training
(reference: cntk-train/src/main/scala/CNTKLearner.scala:52-162) with
jit-compiled steps sharded over a device mesh.
"""

from mmlspark_tpu.train.checkpoint import TrainCheckpointer
from mmlspark_tpu.train.input import DeviceLoader
from mmlspark_tpu.train.learner import JaxLearner, JaxLearnerModel
from mmlspark_tpu.train.loop import TrainConfig, Trainer, make_train_step
from mmlspark_tpu.train.preprocess import (
    DevicePreprocess, envelope_batch, host_preprocess,
)

__all__ = ["DeviceLoader", "DevicePreprocess", "JaxLearner",
           "JaxLearnerModel", "TrainCheckpointer", "TrainConfig",
           "Trainer", "envelope_batch", "host_preprocess",
           "make_train_step"]

"""Training layer: in-process distributed train loops, one-call trainers,
checkpoint/resume, the JaxLearner estimator, and the elastic
fault-tolerant training service (supervisor + recovery policies).

Replaces the reference's out-of-process ``mpiexec cntk`` training
(reference: cntk-train/src/main/scala/CNTKLearner.scala:52-162) with
jit-compiled steps sharded over a device mesh — and its single
exit-code check with supervised recovery: restart from checkpoint,
straggler eviction, and elastic re-scale onto surviving topology
(``train/service.py``, docs/training_service.md).
"""

from mmlspark_tpu.train.checkpoint import (
    CheckpointCorruptError, TrainCheckpointer, reshard_state,
)
from mmlspark_tpu.train.input import DeviceLoader
from mmlspark_tpu.train.learner import JaxLearner, JaxLearnerModel
from mmlspark_tpu.train.loop import TrainConfig, Trainer, make_train_step
from mmlspark_tpu.train.preprocess import (
    DevicePreprocess, envelope_batch, host_preprocess,
)
from mmlspark_tpu.train.service import (
    RecoveryPolicy, ServiceConfig, Topology, TrainSupervisor,
    elastic_stream, service_context,
)

__all__ = ["CheckpointCorruptError", "DeviceLoader", "DevicePreprocess",
           "JaxLearner", "JaxLearnerModel", "RecoveryPolicy",
           "ServiceConfig", "Topology", "TrainCheckpointer",
           "TrainConfig", "Trainer", "TrainSupervisor", "elastic_stream",
           "envelope_batch", "host_preprocess", "make_train_step",
           "reshard_state", "service_context"]

"""JaxLearner — the distributed DNN-training estimator.

The CNTKLearner analog (reference: cntk-train/src/main/scala/
CNTKLearner.scala:52-162). The reference featurizes + assembles, writes the
dataset as CNTK text format to shared storage, generates BrainScript, and
shells out to ``mpiexec -n <gpuCount> cntk ... parallelTrain=true``
(CommandBuilders.scala:79-93), then wraps the resulting model file in
CNTKModel. The TPU-native redesign trains **in-process**:

* featurize/assemble = the same ``Featurize`` path (``reduceAndAssemble``
  analog, reference: cntk-train DataConversion.scala:69-84) — or a direct
  vector/image column,
* no text-file hand-off, no external process: the featurized matrix is
  device-sharded directly (host RAM → HBM, one copy),
* the MPI ring = a ``dp`` mesh axis; 1-bit-SGD all-reduce = XLA ``psum``
  over ICI inserted by the compiler; multi-host spans slices over DCN after
  ``distributed_init`` (no hostfile stubs),
* the result wraps into a :class:`JaxModel` transformer exactly as
  CNTKLearner returns a CNTKModel (CNTKLearner.scala:158-161), and
* mid-training checkpoint/resume comes free from the Trainer (beyond
  reference parity — CNTK epoch checkpoints were not resumable through the
  estimator).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import is_image_column
from mmlspark_tpu.core.stage import Estimator, HasLabelCol, Transformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.models.jax_model import JaxModel, coerce_input_matrix
from mmlspark_tpu.stages.featurize import NUM_FEATURES_TREE_OR_NN
from mmlspark_tpu.stages.indexers import index_values, sorted_levels
from mmlspark_tpu.train.loop import TrainConfig, Trainer


class JaxLearnerModel(Transformer):
    """The fitted result of JaxLearner: (optional featurization) → batched
    JaxModel forward. All three pieces are complex params so the whole
    scoring pipeline round-trips save/load (the reference's CNTKLearner
    result is likewise a persistable CNTKModel, CNTKLearner.scala:158-161)."""

    jax_model = Param(default=None, doc="the fitted JaxModel stage",
                      is_complex=True)
    featurize_model = Param(default=None, doc="fitted featurization "
                            "pipeline (None when input_col was direct)",
                            is_complex=True)
    label_levels = Param(default=None, doc="label values in code order "
                         "(classification only)", is_complex=True)
    final_loss = Param(default=None, doc="last recorded training loss",
                       type_=float)

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_plan_cache", None)
        d.pop("_plan_lock", None)
        return d

    def transform(self, table: DataTable) -> DataTable:
        if self.featurize_model is None:
            return self.jax_model.transform(table)
        # featurize + forward as ONE planned stage list: when the fitted
        # featurization is device-capable (e.g. the single-image-column
        # assembly), the planner fuses it with the model forward into one
        # compiled program — a single H2D upload of the raw uint8 batch per
        # minibatch instead of featurize-on-host + upload-f32-features
        from mmlspark_tpu.core import plan
        feat_stages = list(getattr(self.featurize_model, "stages", None)
                           or [self.featurize_model])
        return plan.execute_stages(feat_stages + [self.jax_model], table,
                                   cache_host=self)


class JaxLearner(Estimator, HasLabelCol):
    """Fits a flax module on a table; returns a JaxLearnerModel."""

    module = Param(default=None, doc="flax module to train (None = MLP "
                   "autosized like the reference's input-dim probe, "
                   "CNTKLearner.scala:72-84)", is_complex=True)
    input_col = Param(default=None, doc="vector/image input column "
                      "(None = auto-featurize all non-label columns)",
                      type_=str)
    feature_columns = Param(default=None, doc="columns to auto-featurize",
                            type_=(list, tuple))
    input_shape = Param(default=None, doc="per-example shape to reshape "
                        "features to (e.g. [32, 32, 3] for conv models)",
                        type_=(list, tuple))
    loss = Param(default="softmax_xent", doc="loss kind", type_=str,
                 validator=Param.one_of("softmax_xent", "sigmoid_xent",
                                        "mse"))
    epochs = Param(default=5, doc="training epochs", type_=int)
    batch_size = Param(default=128, doc="global batch size", type_=int)
    learning_rate = Param(default=1e-3, doc="learning rate", type_=float)
    optimizer = Param(default="adam", doc="optimizer name", type_=str)
    momentum = Param(default=0.9, doc="momentum (momentum optimizer)",
                     type_=float)
    weight_decay = Param(default=0.0, doc="weight decay (adamw)",
                         type_=float)
    seed = Param(default=0, doc="seed", type_=int)
    mesh_spec = Param(default=None, doc="parallelism layout, e.g. "
                      "{'dp': -1, 'fsdp': 2}", type_=dict)
    checkpoint_dir = Param(default=None, doc="mid-training checkpoint dir",
                           type_=str)
    checkpoint_every = Param(default=0, doc="steps between checkpoints",
                             type_=int)
    resume = Param(default=True, doc="resume from latest checkpoint",
                   type_=bool)
    hidden_layers = Param(default=(64,), doc="hidden widths for the default "
                          "MLP", type_=(list, tuple))

    def fit(self, table: DataTable) -> JaxLearnerModel:
        label_col = self.label_col
        is_classification = self.loss in ("softmax_xent", "sigmoid_xent")

        # ---- label handling ----
        labels = table[label_col]
        label_levels: list | None = None
        if is_classification:
            label_levels = sorted_levels(labels)
            y = index_values(labels, label_levels).astype(np.int64)
            num_outputs = max(len(label_levels), 2)
        else:
            y = np.asarray(labels, dtype=np.float64)
            num_outputs = 1
        if self.loss == "sigmoid_xent":
            num_outputs = 1

        # ---- input handling: direct column or auto-featurize ----
        featurize_model = None
        input_col = self.input_col
        if input_col is not None:
            if is_image_column(table, input_col):
                first = table[input_col][0]
                spec = tuple(np.asarray(first["data"]).shape)
            else:
                spec = (table.column_matrix(input_col).shape[1],)
            x = coerce_input_matrix(table, input_col, spec)
        else:
            from mmlspark_tpu.ml.train_classifier import featurize_and_extract
            featurize_model, input_col, x, y = featurize_and_extract(
                table, label_col, y, self.feature_columns,
                NUM_FEATURES_TREE_OR_NN, one_hot=True)

        if self.input_shape:
            x = x.reshape((len(x),) + tuple(int(d) for d in self.input_shape))

        # ---- module: user-provided or autosized MLP ----
        module = self.module
        if module is None:
            from mmlspark_tpu.models.zoo import MLP
            module = MLP(features=tuple(int(w) for w in self.hidden_layers),
                         num_outputs=num_outputs)

        cfg = TrainConfig(
            batch_size=self.batch_size, epochs=self.epochs,
            learning_rate=self.learning_rate, optimizer=self.optimizer,
            momentum=self.momentum, weight_decay=self.weight_decay,
            loss=self.loss, seed=self.seed, mesh_spec=self.mesh_spec,
            checkpoint_dir=self.checkpoint_dir,
            checkpoint_every=self.checkpoint_every, resume=self.resume)
        trainer = Trainer(module, cfg)
        trainer.fit_arrays(x, y.astype(np.float64
                                       if not is_classification
                                       else np.int64))

        import jax
        host_params = jax.tree_util.tree_map(np.asarray, trainer.params)
        bundle = ModelBundle(
            module=module, params=host_params,
            input_spec=tuple(x.shape[1:]),
            output_names=getattr(type(module), "OUTPUT_NAMES", ("logits",)),
            name=f"JaxLearner[{type(module).__name__}]")
        jax_model = JaxModel(model=bundle, input_col=input_col,
                             output_col="scores")
        return JaxLearnerModel(
            jax_model=jax_model, featurize_model=featurize_model,
            label_levels=label_levels,
            final_loss=(float(trainer.history[-1])
                        if trainer.history else None))

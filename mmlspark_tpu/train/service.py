"""Elastic fault-tolerant training service — supervision as policy over
signals.

The reference ran distributed DNN training as a supervised out-of-process
job: ``CNTKLearner`` shelled out to ``mpiexec`` and checked ONE exit code
(reference: cntk-train/src/main/scala/CNTKLearner.scala:140-161) — the job
either finished or died. The TPU-native analog separates the three
concerns that conflation hides:

* **sensors** — the PR 9 anomaly plane: flight-recorder heartbeats (one
  beat per train step / committed batch), the straggler detector's
  fenced step-time exchange, exit codes, and progress deadlines. The
  worker-side :class:`ServiceBeacon` publishes them into the service
  directory, one JSON per worker, atomically.
* **policy** — :class:`RecoveryPolicy`: a PURE decision function from a
  typed :class:`Signal` and the supervision ledger to a typed
  :class:`Action` (restart from checkpoint, evict a straggler, elastic
  re-scale to a smaller topology, fail). Unit-testable without a single
  process spawned.
* **actuator** — :class:`TrainSupervisor`: launches the worker
  generation, watches the sensors, executes the policy's actions, and
  records every decision (``decisions.jsonl`` on disk always; obs
  ``service/*`` events + ``train.service.*`` gauges when the tracer is
  on).

**Elastic re-scale contract.** A generation trains at a rung of the
configured topology ladder. On permanent worker loss the supervisor
drops one rung: the mesh re-forms on the survivors, and the new
generation restores the latest ``TrainCheckpointer`` step with restore
targets built on the NEW mesh — every leaf reshards on read
(``train/checkpoint.py``; in-process rescale uses
:func:`~mmlspark_tpu.train.checkpoint.reshard_state`). Ingest stays
deterministic across the topology change through
:func:`elastic_stream`: batch composition derives from a GLOBAL
seeded walk, each worker taking its rank's slice of every global batch
— so the resumed schedule replays the consumed prefix as no-ops and no
example is dropped or double-consumed across the boundary, at any world
size. The ``check_train_elastic`` tier-1 gate holds the result to the
PR 10 discipline extended to topology change: the recovered run's loss
tail and final params are BIT-identical to an uninterrupted
continuation at the surviving topology.

CLI: ``python tools/train_service.py`` (supervise a worker command, or
run the built-in self-test worker the gate and dryrun use).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import shutil
import subprocess
import threading
import time
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.retry import RetryPolicy
from mmlspark_tpu.obs import fleet as _obs_fleet
from mmlspark_tpu.obs import flight as _obs_flight
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import Counter as _ObsCounter
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.service.core import (
    SupervisedProcess, SupervisorJournal, atomic_write_json, join_pumps,
    read_beacon, terminate_processes,
)

_log = get_logger(__name__)

# worker contract: everything arrives through the environment (the same
# wiring style as mmlspark_tpu.tools.launch), read back by
# ServiceWorkerInfo.from_env()
ENV_DIR = "MMLSPARK_TPU_SERVICE_DIR"
ENV_RANK = "MMLSPARK_TPU_SERVICE_RANK"
ENV_WORLD = "MMLSPARK_TPU_SERVICE_WORLD"
ENV_GENERATION = "MMLSPARK_TPU_SERVICE_GENERATION"
ENV_DEVICES = "MMLSPARK_TPU_SERVICE_DEVICES"
ENV_CKPT = "MMLSPARK_TPU_SERVICE_CKPT"
# set when the supervisor carries a publish policy: the worker brackets
# its result handoff in the lifecycle publish-fence span so worker and
# publisher stitch into one fleet-timeline flow (obs/fleet.py)
ENV_PUBLISH_FENCE = "MMLSPARK_TPU_SERVICE_PUBLISH_FENCE"

# the exit code a preempted worker dies with (EX_TEMPFAIL): policy
# default treats it as PERMANENT capacity loss → immediate re-scale,
# no restart burned on a host that is gone
PREEMPT_EXIT_CODE = 75

WATCH_THREAD = "ServiceWatch"
BEACON_THREAD = "ServiceBeacon"


# the beacon transport lives in the shared supervisor core
# (mmlspark_tpu/service/core.py) — kept under the historical name for
# in-repo callers
_atomic_write_json = atomic_write_json


# ---------------------------------------------------------------------------
# deterministic elastic ingest
# ---------------------------------------------------------------------------


def elastic_batch_indices(n: int, batch_size: int, seed: int,
                          epoch: int) -> Iterator[np.ndarray]:
    """The GLOBAL batch walk for one epoch: a seeded permutation of
    ``range(n)`` cut into ``batch_size`` slices (final slice partial).
    Every topology — any world size, any dp extent — derives its batches
    from THIS walk, which is what makes elastic re-scale replayable: the
    resumed prefix names exactly the examples the dead topology consumed."""
    order = np.random.default_rng(seed + epoch).permutation(n)
    for s in range(0, n, batch_size):
        yield order[s:s + batch_size]


def elastic_stream(x: np.ndarray, y: np.ndarray, *, batch_size: int,
                   seed: int, epochs: int = 1, rank: int = 0,
                   world: int = 1) -> Callable[[], Iterator[tuple]]:
    """Topology-independent sharded ingest for ``Trainer.fit_stream``.

    Returns a zero-arg callable yielding this worker's ``(x, y)`` chunks:
    slice ``rank`` of every global batch from
    :func:`elastic_batch_indices`, across all ``epochs`` in one pass
    (drive it with ``TrainConfig(epochs=1)`` — the walk owns the epoch
    structure, so the schedule fingerprint is identical at every world
    size). Chunk size equals the local batch size, so ``fit_stream``'s
    rebatcher maps chunks 1:1 onto steps and the assembled GLOBAL batch
    is the process-order concatenation of the walk's slices — the same
    rows in the same order whether one worker holds them all or ``world``
    workers hold a slice each.

    Sharded walks require ``batch_size | len(x)``: a short tail batch
    would slice unevenly across ranks (some slices short or empty),
    desynchronizing the per-rank chunk streams — from the next epoch on
    the assembled "global" batch would silently mix rows of different
    walk positions. That is a LOUD error here, not a masked tail; pad or
    trim the dataset (a world of 1 keeps the masked-tail behavior —
    there is no cross-rank pairing to corrupt). The same divisibility is
    what makes cross-topology replay bit-compatible anyway.
    """
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside world {world}")
    if batch_size % world:
        raise ValueError(
            f"batch_size {batch_size} must divide over {world} workers")
    if world > 1 and len(x) % batch_size:
        raise ValueError(
            f"elastic_stream with world {world} requires batch_size "
            f"({batch_size}) to divide the dataset ({len(x)} rows): a "
            "partial tail batch slices unevenly across ranks and "
            "desynchronizes the per-rank chunk streams from the next "
            "epoch on — pad or trim the dataset")
    bs_local = batch_size // world

    def source() -> Iterator[tuple]:
        for epoch in range(epochs):
            for idx in elastic_batch_indices(len(x), batch_size, seed,
                                             epoch):
                mine = idx[rank * bs_local:(rank + 1) * bs_local]
                if len(mine):  # world==1: the masked tail may be short
                    yield x[mine], y[mine]

    return source


# ---------------------------------------------------------------------------
# worker side: env contract + liveness beacon
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ServiceWorkerInfo:
    """This worker's identity under the supervisor (from the env)."""

    service_dir: str
    rank: int
    world: int
    generation: int
    devices: int | None
    checkpoint_dir: str | None

    @staticmethod
    def from_env() -> "ServiceWorkerInfo | None":
        service_dir = os.environ.get(ENV_DIR)
        if not service_dir:
            return None
        devices = os.environ.get(ENV_DEVICES)
        return ServiceWorkerInfo(
            service_dir=service_dir,
            rank=int(os.environ.get(ENV_RANK, "0")),
            world=int(os.environ.get(ENV_WORLD, "1")),
            generation=int(os.environ.get(ENV_GENERATION, "0")),
            devices=int(devices) if devices else None,
            checkpoint_dir=os.environ.get(ENV_CKPT) or None)

    def beacon_path(self) -> str:
        return os.path.join(self.service_dir, f"beacon_{self.rank}.json")

    def result_path(self) -> str:
        return os.path.join(
            self.service_dir,
            f"result_gen{self.generation}_rank{self.rank}.json")


class ServiceBeacon:
    """Worker-side liveness publisher: samples the PR 9 sensors — the
    flight recorder's heartbeat table (one beat per train step /
    committed batch) and the registry's straggler series — and writes
    them atomically to ``beacon_<rank>.json`` on an interval. The
    supervisor's deadline monitoring and straggler-evict policy read
    ONLY this file: worker and supervisor share no memory, so the same
    sensor surface works across hosts (a shared filesystem is the
    transport, like the checkpoint itself)."""

    def __init__(self, info: ServiceWorkerInfo, interval_s: float = 0.25):
        self.info = info
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"{BEACON_THREAD}[{info.rank}]",
            daemon=True)

    def start(self) -> "ServiceBeacon":
        self._thread.start()
        return self

    def _sample(self, status: str) -> dict:
        sample: dict[str, Any] = {
            "rank": self.info.rank, "pid": os.getpid(),
            "generation": self.info.generation,
            "ts": time.time(), "status": status,
            "progress": 0, "busy": False,
            "stragglers": 0, "host_step_ms": {},
            "counters": [],
        }
        rec = _obs_flight._rec
        if rec is not None:
            beats = rec.heartbeats()
            sample["heartbeats"] = beats
            sample["progress"] += int(sum(hb["beats"]
                                          for hb in beats.values()))
            sample["busy"] = any(hb["busy"] for hb in beats.values())
        # straggler sensors ride the registry (obs/anomaly.py publishes
        # them on the fenced liveness exchange); iterate the interned
        # metric objects — no string key parsing. The train.* counter
        # EXCERPT is the supervisor's fleet-aggregation feed: it reads
        # per-worker deltas off the beacons and publishes
        # `train.fleet.*` series (docs/training_service.md)
        for m in _obs_registry().iter_metrics():
            labels = dict(m.labels)
            if m.name == "train.steps":
                sample["progress"] += int(m.value)
            elif m.name == "train.stragglers":
                sample["stragglers"] += int(m.value)
            elif m.name == "train.host_step_ms":
                sample["host_step_ms"][str(labels.get("host"))] = m.value
            elif m.name == "train.loss" and hasattr(m, "values"):
                # the eval series (Trainer._note_loss publishes every
                # logged loss into this windowed histogram) — what the
                # supervisor's lifecycle EvalGate judges mid-run
                sample["eval"] = [float(v) for v in m.values()]
            if isinstance(m, _ObsCounter) \
                    and m.name.startswith("train."):
                sample["counters"].append([m.name, labels, m.value])
        return sample

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                _atomic_write_json(self.info.beacon_path(),
                                   self._sample("running"))
            except Exception:  # pragma: no cover - beacon never kills
                pass           # the worker it reports on

    def close(self, status: str = "exited") -> None:
        """Stop the publisher thread (joined, never leaked) and write the
        terminal status so the supervisor can distinguish a clean exit
        from a vanished process."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        try:
            _atomic_write_json(self.info.beacon_path(),
                               self._sample(status))
        except Exception:  # pragma: no cover - best-effort terminal write
            pass


@contextlib.contextmanager
def service_context(beacon_interval_s: float = 0.25):
    """Worker-side entry: read the supervisor's env contract, start the
    liveness beacon, and guarantee its shutdown. Yields the
    :class:`ServiceWorkerInfo` (or None when not running under a
    supervisor — library code can call this unconditionally).

    The flight recorder and obs tracer are enabled through their own env
    vars (``MMLSPARK_TPU_FLIGHT``/``MMLSPARK_TPU_OBS``, which the
    supervisor sets on the worker env) — this context adds no competing
    enable path."""
    info = ServiceWorkerInfo.from_env()
    if info is None:
        yield None
        return
    os.makedirs(info.service_dir, exist_ok=True)
    beacon = ServiceBeacon(info, interval_s=beacon_interval_s).start()
    try:
        yield info
    except BaseException:
        beacon.close(status="crashed")
        raise
    else:
        beacon.close(status="exited")


# ---------------------------------------------------------------------------
# signals, actions, policy
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WorkerExit:
    """A worker process exited with a nonzero code (crash, preemption,
    or a signal — negative codes are deaths by signal)."""
    rank: int
    code: int


@dataclasses.dataclass(frozen=True)
class WorkerHang:
    """A busy worker made no progress (beacon beats + step counters
    frozen) past the deadline."""
    rank: int
    stalled_s: float


@dataclasses.dataclass(frozen=True)
class WorkerStraggling:
    """The straggler detector named this worker's host in ``count``
    successive liveness windows."""
    rank: int
    count: int


Signal = Any  # WorkerExit | WorkerHang | WorkerStraggling


@dataclasses.dataclass(frozen=True)
class Restart:
    reason: str
    delay_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class Rescale:
    reason: str
    evict_rank: int | None = None


@dataclasses.dataclass(frozen=True)
class Fail:
    reason: str


@dataclasses.dataclass(frozen=True)
class Proceed:
    reason: str = ""


Action = Any  # Restart | Rescale | Fail | Proceed


@dataclasses.dataclass
class Ledger:
    """The supervision history the policy conditions on."""
    restarts_used: int = 0
    rung: int = 0
    rungs_total: int = 1

    @property
    def can_rescale(self) -> bool:
        return self.rung + 1 < self.rungs_total


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Signal → action, pure. The table (docs/training_service.md):

    ==========================  =========================================
    signal                      action
    ==========================  =========================================
    exit in preempt codes       re-scale (permanent capacity loss)
    exit nonzero / hang         restart from latest checkpoint while the
                                budget lasts, backoff-paced; then
                                re-scale (if a rung remains and
                                ``rescale_on_exhausted``), else fail
    straggler named ≥ N times   evict the named worker → re-scale
    straggler below N           proceed (transient skew is not a fault)
    ==========================  =========================================

    ``restart_backoff`` reuses the :class:`RetryPolicy` schedule (its
    ``retry_on`` is unused here; ``max_attempts`` bounds nothing — the
    restart budget is ``max_restarts``).
    """

    max_restarts: int = 2
    restart_backoff: RetryPolicy = RetryPolicy(
        max_attempts=64, base_delay_s=0.5, max_delay_s=30.0, jitter=0.5)
    preempt_exit_codes: tuple[int, ...] = (PREEMPT_EXIT_CODE,)
    rescale_on_exhausted: bool = True
    hang_timeout_s: float | None = None
    evict_straggler_after: int | None = None

    def _backoff(self, k: int) -> float:
        for i, d in enumerate(self.restart_backoff.delays()):
            if i == k:
                return d
        return self.restart_backoff.max_delay_s

    def _lost(self, reason: str, ledger: Ledger) -> Action:
        if ledger.restarts_used < self.max_restarts:
            return Restart(reason,
                           delay_s=self._backoff(ledger.restarts_used))
        if self.rescale_on_exhausted and ledger.can_rescale:
            return Rescale(f"{reason}; restart budget "
                           f"({self.max_restarts}) exhausted")
        return Fail(f"{reason}; restart budget exhausted and no smaller "
                    "topology to re-scale to")

    def decide(self, sig: Signal, ledger: Ledger) -> Action:
        if isinstance(sig, WorkerExit):
            if sig.code == 0:
                return Proceed("clean exit")
            if sig.code in self.preempt_exit_codes:
                if ledger.can_rescale:
                    return Rescale(
                        f"worker {sig.rank} preempted (exit {sig.code})",
                        evict_rank=sig.rank)
                return Fail(f"worker {sig.rank} preempted and no smaller "
                            "topology to re-scale to")
            return self._lost(
                f"worker {sig.rank} died (exit {sig.code})", ledger)
        if isinstance(sig, WorkerHang):
            return self._lost(
                f"worker {sig.rank} hung ({sig.stalled_s:.1f}s without "
                "progress while busy)", ledger)
        if isinstance(sig, WorkerStraggling):
            if (self.evict_straggler_after is not None
                    and sig.count >= self.evict_straggler_after):
                if ledger.can_rescale:
                    return Rescale(
                        f"worker {sig.rank} named straggler in "
                        f"{sig.count} windows", evict_rank=sig.rank)
                return Proceed("straggler persists but no smaller "
                               "topology; keeping it")
            return Proceed("straggler below eviction threshold")
        raise TypeError(f"unknown signal {sig!r}")


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Topology:
    """One rung of the elastic ladder: how many worker processes, and —
    on the hardware-free dryrun rig — how many virtual CPU devices each
    gets (``None`` inherits the environment, i.e. real accelerators)."""
    world: int = 1
    devices: int | None = None


@dataclasses.dataclass
class ServiceConfig:
    """Supervisor configuration. ``cmd`` is the worker argv, launched
    ``world`` times per generation with the env contract set
    (rank/world/generation/devices/service dir/checkpoint dir)."""

    cmd: Sequence[str]
    service_dir: str
    topologies: tuple[Topology, ...] = (Topology(),)
    checkpoint_dir: str | None = None
    policy: RecoveryPolicy = dataclasses.field(default_factory=RecoveryPolicy)
    poll_s: float = 0.1
    grace_seconds: float = 10.0
    worker_obs: bool = True      # MMLSPARK_TPU_OBS=1 on workers (the
    #                              straggler sensors publish through it)
    worker_flight: bool = True   # flight recorder dir per worker under
    #                              service_dir/flight/ (post-mortems land
    #                              where the supervisor can find them)
    worker_fleet: bool = True    # propagate this process's fleet dir
    #                              (obs/fleet.py, MMLSPARK_TPU_FLEET) so
    #                              workers export telemetry snapshots
    #                              into the same fleet plane
    snapshot_recovery: bool = True  # archive the checkpoint dir at each
    #                                 re-scale (the exact recovery point,
    #                                 for audit/bit-compat verification)
    coordinator: str | None = None  # world>1: host:port of rank 0
    extra_env: dict[str, str] = dataclasses.field(default_factory=dict)
    publish: Any | None = None   # lifecycle.PublishPolicy: eval-gate and
    #                              dark-publish passing checkpoints to a
    #                              ModelRepo on clean completion (and
    #                              optionally every K checkpoints) —
    #                              the train→serve deployment plane
    #                              (docs/lifecycle.md)

    def __post_init__(self) -> None:
        if not self.topologies:
            raise ValueError("at least one topology rung is required")
        for i, t in enumerate(self.topologies[1:], 1):
            prev = self.topologies[i - 1]
            if t.world > prev.world:
                raise ValueError(
                    "topology ladder must not GROW across rungs (rung "
                    f"{i} has world {t.world} > {prev.world}) — rungs "
                    "are what remains after capacity loss")
            if (t.devices is not None and prev.devices is not None
                    and t.devices > prev.devices):
                raise ValueError(
                    "topology ladder must not GROW across rungs (rung "
                    f"{i} has devices {t.devices} > {prev.devices}) — "
                    "rungs are what remains after capacity loss")


@dataclasses.dataclass
class GenerationReport:
    generation: int
    topology: Topology
    exit_codes: dict[int, int | None]
    signal: Any = None
    action: Any = None


@dataclasses.dataclass
class ServiceReport:
    ok: bool = False
    reason: str = ""
    generations: list = dataclasses.field(default_factory=list)
    restarts: int = 0
    rescales: int = 0
    evictions: int = 0
    snapshots: list = dataclasses.field(default_factory=list)

    @property
    def final_topology(self) -> Topology | None:
        return (self.generations[-1].topology
                if self.generations else None)


class _Worker(SupervisedProcess):
    """One supervised worker process + its output pump and progress
    tracking (the shared :class:`SupervisedProcess` core under the
    train service's pump naming)."""

    def __init__(self, rank: int, proc: subprocess.Popen):
        super().__init__(rank, proc, log_prefix="service worker",
                         thread_name=f"{WATCH_THREAD}[pump{rank}]")


class TrainSupervisor:
    """Launch, watch, and recover a supervised training job (see module
    docstring). ``run()`` blocks until the job completes at some rung of
    the topology ladder or the policy gives up, and returns the
    :class:`ServiceReport` with every signal → action decision taken."""

    def __init__(self, cfg: ServiceConfig):
        self.cfg = cfg
        os.makedirs(cfg.service_dir, exist_ok=True)
        self._decisions_path = os.path.join(cfg.service_dir,
                                            "decisions.jsonl")
        # every supervisor decision is an event: appended to the on-disk
        # decisions.jsonl ALWAYS (supervision forensics must not depend
        # on telemetry being on), mirrored as an obs service/<kind>
        # event + train.service.* counters when the tracer is enabled —
        # the shared SupervisorJournal discipline (service/core.py)
        self._journal = SupervisorJournal(
            self._decisions_path, event_prefix="service", cat="service",
            counter_prefix="train.service.",
            counter_kinds=("restart", "rescale", "evict", "worker_exit",
                           "hang"),
            log_label="train service")
        self._straggler_total = 0  # global verdict windows this generation
        self._publisher = None
        if cfg.publish is not None:
            # lazy import: supervising plain training jobs must not pull
            # the lifecycle/models planes in
            from mmlspark_tpu.lifecycle.publish import Publisher
            self._publisher = Publisher(
                cfg.publish, cfg.service_dir,
                run_id=f"train-{os.getpid()}-{int(time.time())}",
                train_journal=self._decisions_path)

    # -- observability of the supervisor itself --

    def _record(self, kind: str, payload: dict) -> None:
        self._journal.record(kind, payload)

    def _gauges(self, generation: int, topo: Topology) -> None:
        if _obs_rt._enabled:
            reg = _obs_registry()
            reg.gauge("train.service.generation").set(generation)
            reg.gauge("train.service.world").set(topo.world)
            if topo.devices is not None:
                reg.gauge("train.service.devices").set(topo.devices)

    # -- process management --

    def _spawn(self, generation: int, topo: Topology) -> list[_Worker]:
        self._straggler_total = 0  # verdict windows are per-generation
        coordinator = self.cfg.coordinator
        if topo.world > 1 and coordinator is None:
            import socket
            with socket.socket() as s:
                s.bind(("localhost", 0))
                coordinator = f"localhost:{s.getsockname()[1]}"
        workers = []
        for rank in range(topo.world):
            env = dict(os.environ)
            env.update(self.cfg.extra_env)
            env[ENV_DIR] = self.cfg.service_dir
            env[ENV_RANK] = str(rank)
            env[ENV_WORLD] = str(topo.world)
            env[ENV_GENERATION] = str(generation)
            if self.cfg.checkpoint_dir:
                env[ENV_CKPT] = self.cfg.checkpoint_dir
            if topo.devices is not None:
                env[ENV_DEVICES] = str(topo.devices)
                env["JAX_PLATFORMS"] = "cpu"
                # REPLACE any inherited device-count flag: the ladder's
                # whole point is that rungs differ in device count, and
                # a supervisor running inside an 8-device test rig would
                # otherwise hand every rung the rig's count
                flags = [f for f in env.get("XLA_FLAGS", "").split()
                         if "xla_force_host_platform_device_count"
                         not in f]
                flags.append("--xla_force_host_platform_device_count="
                             f"{topo.devices}")
                env["XLA_FLAGS"] = " ".join(flags)
            if topo.world > 1:
                env["MMLSPARK_TPU_COORDINATOR"] = coordinator
                env["MMLSPARK_TPU_NUM_PROCESSES"] = str(topo.world)
                env["MMLSPARK_TPU_PROCESS_ID"] = str(rank)
            if self.cfg.worker_obs:
                env.setdefault("MMLSPARK_TPU_OBS", "1")
            if self._publisher is not None:
                env.setdefault(ENV_PUBLISH_FENCE, "1")
            if self.cfg.worker_flight:
                env.setdefault("MMLSPARK_TPU_FLIGHT", os.path.join(
                    self.cfg.service_dir, "flight",
                    f"gen{generation}_rank{rank}"))
            if self.cfg.worker_fleet:
                fdir = _obs_fleet.fleet_dir()
                if fdir:
                    env.setdefault("MMLSPARK_TPU_FLEET", fdir)
            proc = subprocess.Popen(
                list(self.cfg.cmd), env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, errors="replace")
            workers.append(_Worker(rank, proc))
            # supervisor-side flight heartbeat per worker: a supervisor
            # with its own recorder on shows which worker stopped moving
            # in ITS post-mortems too. Registered IDLE: only beacon
            # progress marks it busy — an armed-busy row with no beacon
            # evidence (compile, a worker that never beacons) would
            # ripen into spurious watchdog hang dumps, the dead-busy-row
            # class PR 9 fixed for drain_barrier
            rec = _obs_flight._rec
            if rec is not None:
                rec.arm(f"service/worker{rank}")
                rec.disarm(f"service/worker{rank}")
        self._record("launch", {
            "generation": generation, "world": topo.world,
            "devices": topo.devices, "pids":
                {w.rank: w.proc.pid for w in workers}})
        self._gauges(generation, topo)
        return workers

    def _terminate(self, workers: list[_Worker]) -> None:
        terminate_processes(workers, self.cfg.grace_seconds)
        self._forget(workers)

    def _forget(self, workers: list[_Worker]) -> None:
        """Shutdown hygiene: drop dead workers' supervisor-side flight
        heartbeat rows (a long-lived supervisor with generation churn
        must not bloat every dump's heartbeat table — nor ripen dead
        busy rows into spurious hang dumps) and join the output pumps
        (no stray threads after an evict)."""
        rec = _obs_flight._rec
        for w in workers:
            if rec is not None:
                rec.forget(f"service/worker{w.rank}")
        join_pumps(workers)

    # -- sensor reads --

    def _read_beacon(self, generation: int, rank: int) -> dict | None:
        # generation-checked (a stale file from the previous generation
        # is not this worker) — shared with the fleet supervisor
        return read_beacon(self.cfg.service_dir, rank, generation)

    def _poll_sensors(self, generation: int,
                      workers: list[_Worker]) -> Signal | None:
        policy = self.cfg.policy
        rec = _obs_flight._rec
        beacons: dict[int, dict | None] = {}
        for w in workers:
            b = self._read_beacon(generation, w.rank)
            beacons[w.rank] = b
            if b is None:
                # no current-generation liveness signal at all: keep the
                # supervisor-side heartbeat row idle (no evidence of
                # busy), but a worker wedged BEFORE its first beacon
                # (backend/distributed init, a dead beacon thread) must
                # still hit the deadline — absence of the signal past
                # the timeout IS the hang signal (baseline: spawn time)
                if rec is not None:
                    rec.disarm(f"service/worker{w.rank}")
                if (policy.hang_timeout_s is not None
                        and w.proc.poll() is None):
                    stalled = time.monotonic() - w.progress_ts
                    if stalled > policy.hang_timeout_s:
                        return WorkerHang(w.rank, stalled)
                continue
            progress = int(b.get("progress", 0))
            if progress != w.last_progress:
                w.last_progress = progress
                w.progress_ts = time.monotonic()
                if rec is not None:
                    rec.beat(f"service/worker{w.rank}")
            elif not b.get("busy") and rec is not None:
                rec.disarm(f"service/worker{w.rank}")  # idle, not hung
            elif (policy.hang_timeout_s is not None and b.get("busy")
                  and w.proc.poll() is None):
                stalled = time.monotonic() - w.progress_ts
                if stalled > policy.hang_timeout_s:
                    return WorkerHang(w.rank, stalled)
        # fleet aggregation: ONE read of the beacon set produces both
        # the published `train.fleet.*` series and the inputs the
        # straggler verdict below consumes — policy and telemetry see
        # the same numbers by construction, never two derivations
        agg = self._fleet_aggregates(beacons)
        self._publish_fleet(workers, beacons, agg)
        # straggler verdicts are GLOBAL: the fenced exchange increments
        # train.stragglers identically in EVERY process, so the window
        # count is the MAX across beacons — summing per-beacon increments
        # would count each verdict world× and evict world× too early
        total = agg["straggler_windows"]
        if total > self._straggler_total:
            delta = total - self._straggler_total
            hosts = agg["host_step_ms"]
            if hosts:
                slow = max(hosts, key=lambda h: hosts[h] or 0.0)
                for target in workers:
                    if str(target.rank) == str(slow):
                        # commit the tally only WITH attribution: a
                        # beacon sampled between the counter bump and
                        # the gauge publication must not silently eat
                        # verdict windows — leave them for the next poll
                        self._straggler_total = total
                        target.straggler_hits += delta
                        return WorkerStraggling(
                            target.rank, target.straggler_hits)
        return None

    def _fleet_aggregates(self, beacons: dict[int, dict | None]) -> dict:
        """Merge one poll's beacons into the fleet view: live worker
        count, summed progress, the GLOBAL straggler verdict-window
        count (max across beacons — every process counts each fenced
        verdict identically), and the per-host step-time table (from
        the beacon that has witnessed the most verdicts — the most
        current attribution). ``workers`` counts only RUNNING-status
        beacons: the final terminal-beacon read after a clean
        completion folds in the last counter deltas, and an
        exited/crashed beacon must not leave the liveness gauge
        reporting dead workers as live on an idle supervisor.
        Progress/straggler/step-time reads stay cumulative truth
        whatever the status."""
        live = [b for b in beacons.values() if b]
        host_step_ms: dict = {}
        for b in sorted(live, key=lambda b: int(b.get("stragglers", 0)),
                        reverse=True):
            if b.get("host_step_ms"):
                host_step_ms = b["host_step_ms"]
                break
        return {
            "workers": sum(1 for b in live
                           if b.get("status", "running") == "running"),
            "progress": sum(int(b.get("progress", 0)) for b in live),
            "straggler_windows": max(
                (int(b.get("stragglers", 0)) for b in live), default=0),
            "host_step_ms": host_step_ms,
        }

    def _publish_fleet(self, workers: list[_Worker],
                       beacons: dict[int, dict | None],
                       agg: dict) -> None:
        """Publish the beacon-derived fleet aggregates as first-class
        `train.fleet.*` series in the SUPERVISOR's registry (tracer-
        gated, like every supervisor series): liveness/progress/skew
        gauges, plus per-worker DELTAS of the beacon registry excerpts
        re-accumulated as `train.fleet.<counter>{rank=…}` counters — so
        downstream consumers (the timeseries sampler, a fleet exporter
        on the supervisor, /metrics scrapes) read one aggregated
        surface instead of re-deriving from raw beacon files."""
        if not _obs_rt._enabled:
            return
        reg = _obs_registry()
        reg.gauge("train.fleet.workers").set(agg["workers"])
        reg.gauge("train.fleet.progress").set(agg["progress"])
        reg.gauge("train.fleet.straggler_windows").set(
            agg["straggler_windows"])
        for host, ms in agg["host_step_ms"].items():
            if isinstance(ms, (int, float)):
                reg.gauge("train.fleet.host_step_ms",
                          host=str(host)).set(float(ms))
        for w in workers:
            b = beacons.get(w.rank)
            if not b:
                continue
            for row in b.get("counters") or ():
                try:
                    name, labels, value = row
                    value = float(value)
                    labels = {str(k): v for k, v in dict(labels).items()}
                except (TypeError, ValueError):
                    continue
                key = (name, tuple(sorted(labels.items())))
                last = w.counter_last.get(key)
                # a backward value is a restarted worker's fresh
                # registry: the new total is all new progress
                delta = value if (last is None or value < last) \
                    else value - last
                w.counter_last[key] = value
                if delta > 0:
                    # rank= is the fleet dimension: a worker counter
                    # that already carries its own rank label (worker
                    # code is arbitrary) is overridden, never a
                    # duplicate-keyword TypeError killing the watch loop
                    flabels = {**labels, "rank": w.rank}
                    reg.counter(
                        "train.fleet." + name[len("train."):],
                        **flabels).add(delta)

    def _watch(self, generation: int,
               workers: list[_Worker]) -> Signal | None:
        """Block until the generation finishes (returns None) or a fault
        signal fires (returns it; remaining workers still running).
        Re-entrant for the same worker set: a signal the policy declines
        to act on (Proceed) resumes the watch without re-reporting
        already-seen exits."""
        while True:
            for w in workers:
                code = w.proc.poll()
                if code is not None and not getattr(w, "exit_recorded",
                                                    False):
                    w.exit_recorded = True
                    self._record("worker_exit", {
                        "generation": generation, "rank": w.rank,
                        "code": code})
                    rec = _obs_flight._rec
                    if rec is not None:
                        rec.forget(f"service/worker{w.rank}")
                    if code != 0:
                        return WorkerExit(w.rank, code)
            if all(w.proc.poll() is not None for w in workers):
                return None
            sig = self._poll_sensors(generation, workers)
            if sig is not None:
                return sig
            if self._publisher is not None:
                self._publish_poll(generation)
            time.sleep(self.cfg.poll_s)

    # -- eval-gated publication (the lifecycle deployment plane) --

    def _publish_poll(self, generation: int) -> None:
        """Mid-run publication sensors, ridden on the watch loop: retry
        a torn publish, then feed the every-K-checkpoints gate off
        rank 0's beacon eval series (docs/lifecycle.md). Never raises —
        a broken publish hook must not take supervision down."""
        pub = self._publisher
        try:
            record = pub.retry_pending()
            if record is None:
                beacon = self._read_beacon(generation, 0) or {}
                record = pub.on_checkpoint_poll(
                    generation, self.cfg.checkpoint_dir,
                    beacon.get("eval") or [])
            if record:
                self._record("publish", {
                    "generation": generation, "model": record["model"],
                    "version": record["version"],
                    "lifecycle_journal": pub.journal.path})
        except Exception as e:  # pragma: no cover - defensive
            _log.warning("train service: publish poll failed: %s", e)

    def _publish_complete(self, generation: int) -> None:
        """Clean-completion publication: judge rank 0's result file
        (the worker bracketed its write in the publish-fence span; the
        gate + publish here is the other side of that fence). The
        cross-reference lands in BOTH journals: the lifecycle record
        carries the train decisions path, this record carries the
        lifecycle decisions path."""
        pub = self._publisher
        if pub is None:
            return
        try:
            pub.retry_pending()
            path = os.path.join(
                self.cfg.service_dir,
                f"result_gen{generation}_rank0.json")
            with open(path, encoding="utf-8") as f:
                result = json.load(f)
            record = pub.on_complete(generation, result)
            if record:
                self._record("publish", {
                    "generation": generation, "model": record["model"],
                    "version": record["version"],
                    "lifecycle_journal": pub.journal.path})
        except Exception as e:
            _log.warning("train service: completion publish failed: %s",
                         e)

    def _snapshot(self, generation: int) -> str | None:
        """Archive the checkpoint dir at the recovery point — the state
        the re-scaled generation will restore, preserved for audit (the
        bit-compat gate re-runs an uninterrupted continuation from it)."""
        ck = self.cfg.checkpoint_dir
        if not (self.cfg.snapshot_recovery and ck and os.path.isdir(ck)):
            return None
        dest = os.path.join(self.cfg.service_dir,
                            f"recovery_gen{generation}")
        if os.path.exists(dest):  # pragma: no cover - re-entry
            shutil.rmtree(dest)
        shutil.copytree(ck, dest)
        return dest

    # -- the supervision loop --

    def run(self) -> ServiceReport:
        report = ServiceReport()
        ledger = Ledger(rungs_total=len(self.cfg.topologies))
        generation = 0
        workers: list[_Worker] = []
        try:
            while True:
                topo = self.cfg.topologies[ledger.rung]
                workers = self._spawn(generation, topo)
                while True:
                    sig = self._watch(generation, workers)
                    if sig is None:
                        action = None
                        break
                    action = self.cfg.policy.decide(sig, ledger)
                    if not isinstance(action, Proceed):
                        break
                    # policy declined to act (e.g. straggler below the
                    # eviction threshold): the generation keeps running,
                    # resume the watch
                    self._record("proceed", {"generation": generation,
                                             "signal": repr(sig),
                                             "reason": action.reason})
                gen_report = GenerationReport(
                    generation, topo,
                    {w.rank: w.proc.poll() for w in workers}, signal=sig,
                    action=action)
                report.generations.append(gen_report)
                if sig is None:
                    # one final fleet publication off the TERMINAL
                    # beacons: the watch loop returns the moment every
                    # worker exits, which can precede its last
                    # mid-run sensor poll — without this read the
                    # train.fleet.* aggregates would understate the
                    # completed generation by up to one beacon interval
                    beacons = {w.rank:
                               self._read_beacon(generation, w.rank)
                               for w in workers}
                    self._publish_fleet(
                        workers, beacons,
                        self._fleet_aggregates(beacons))
                    self._forget(workers)
                    workers = []
                    self._publish_complete(generation)
                    report.ok = True
                    report.reason = (
                        f"completed at rung {ledger.rung} "
                        f"(world={topo.world}, devices={topo.devices})")
                    self._record("done", {"generation": generation,
                                          "rung": ledger.rung})
                    return report
                self._terminate(workers)
                workers = []
                if isinstance(action, Restart):
                    ledger.restarts_used += 1
                    report.restarts += 1
                    self._record("restart", {
                        "generation": generation, "reason": action.reason,
                        "delay_s": round(action.delay_s, 3),
                        "restarts_used": ledger.restarts_used})
                    if action.delay_s:
                        time.sleep(action.delay_s)
                    generation += 1
                    continue
                if isinstance(action, Rescale):
                    snap = self._snapshot(generation + 1)
                    if snap:
                        report.snapshots.append(snap)
                    ledger.rung += 1
                    report.rescales += 1
                    if action.evict_rank is not None:
                        report.evictions += 1
                        self._record("evict", {
                            "generation": generation,
                            "rank": action.evict_rank,
                            "reason": action.reason})
                    self._record("rescale", {
                        "generation": generation, "reason": action.reason,
                        "rung": ledger.rung,
                        "world": self.cfg.topologies[ledger.rung].world,
                        "devices":
                            self.cfg.topologies[ledger.rung].devices,
                        "snapshot": snap})
                    generation += 1
                    continue
                report.ok = False
                report.reason = action.reason
                self._record("fail", {"generation": generation,
                                      "reason": action.reason})
                return report
        finally:
            if workers:
                self._terminate(workers)
            # supervisor shutdown hygiene across ALL generations: no
            # service/ heartbeat rows may survive the run
            rec = _obs_flight._rec
            if rec is not None:
                for name in list(rec.heartbeats()):
                    if name.startswith("service/worker"):
                        rec.forget(name)


# ---------------------------------------------------------------------------
# built-in self-test worker (the gate / dryrun workload)
# ---------------------------------------------------------------------------


def selftest_data(n: int = 256, dim: int = 8,
                  seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """The deterministic XOR dataset the self-test worker, the
    ``check_train_elastic`` gate, and the dryrun all share. ``n`` is a
    multiple of the gate's batch size, so the elastic walk has no
    partial tail batch (bit-compatible cross-topology replay)."""
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, dim)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    return x, y


def selftest_config(checkpoint_dir: str | None) -> Any:
    """The self-test schedule: 2 passes over 256 rows at global batch 32
    → 16 steps, checkpoint every 5. Identical at every ladder rung (the
    fingerprint the resumed generation must match)."""
    from mmlspark_tpu.train.loop import TrainConfig
    return TrainConfig(batch_size=32, epochs=1, learning_rate=5e-3,
                       optimizer="momentum", log_every=1, seed=0,
                       donate_state=False, prefetch_depth=2,
                       checkpoint_dir=checkpoint_dir, checkpoint_every=5,
                       resume=True)


SELFTEST_EPOCH_PASSES = 2


def run_selftest_worker() -> int:
    """One supervised training worker: MLP on the shared XOR set through
    ``Trainer.fit_stream`` with :func:`elastic_stream` ingest, mesh
    ``dp×fsdp`` over whatever devices this generation granted. Supports
    induced preemption (``MMLSPARK_TPU_SERVICE_DIE_AT_STEP=<k>`` +
    ``MMLSPARK_TPU_SERVICE_DIE_GEN=<g>``: hard ``os._exit(75)`` after
    the walk yields ``k`` chunks in generation ``g`` — mid-training,
    no cleanup, like a preempted pod worker). Writes the loss history,
    final step, and full final params to ``result_gen<g>_rank<r>`` files
    for the bit-compat gate."""
    with service_context() as info:
        if info is None:
            raise SystemExit("not under a train service supervisor "
                             f"({ENV_DIR} unset)")
        import jax
        # pin the platform only when the supervisor granted virtual
        # devices (Topology.devices set ⇒ JAX_PLATFORMS=cpu in our env);
        # a devices=None rung inherits the environment — real
        # accelerators on a TPU host
        plat = os.environ.get("JAX_PLATFORMS")
        if plat and info.devices is not None:
            try:
                jax.config.update("jax_platforms", plat)
            except Exception:  # pragma: no cover - backend already up
                pass
        if info.world > 1:
            from mmlspark_tpu.utils.env import distributed_init
            distributed_init()
        from mmlspark_tpu.models.zoo import MLP
        from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
        from mmlspark_tpu.train.loop import Trainer

        n_dev = len(jax.devices())
        mesh = make_mesh(MeshSpec(
            dp=-1, fsdp=2 if n_dev % 2 == 0 else 1))
        cfg = selftest_config(info.checkpoint_dir)
        # a non-default data seed degrades the run on purpose (different
        # data → different trained params): how the lifecycle gate
        # manufactures a candidate whose answers drift from the fleet's
        x, y = selftest_data(seed=int(os.environ.get(
            "MMLSPARK_TPU_SERVICE_SELFTEST_DATA_SEED", "0")))

        die_at = int(os.environ.get("MMLSPARK_TPU_SERVICE_DIE_AT_STEP",
                                    "0"))
        die_gen = int(os.environ.get("MMLSPARK_TPU_SERVICE_DIE_GEN", "0"))
        die_rank = int(os.environ.get("MMLSPARK_TPU_SERVICE_DIE_RANK",
                                      "0"))
        die_here = (die_at and info.generation == die_gen
                    and info.rank == die_rank)
        base = elastic_stream(x, y, batch_size=cfg.batch_size,
                              seed=cfg.seed, epochs=SELFTEST_EPOCH_PASSES,
                              rank=info.rank, world=info.world)

        def source():
            for k, chunk in enumerate(base(), 1):
                if die_here and k > die_at:
                    os._exit(PREEMPT_EXIT_CODE)  # induced preemption
                yield chunk

        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
        tr.fit_stream(source, input_spec=(x.shape[1],))

        steps = int(np.asarray(tr.state["step"]))

        def host_full(leaf):
            # a world>1 mesh fsdp-shards params ACROSS processes —
            # np.asarray on a non-addressable global array raises; gather
            # the full value first (replicated params pass straight through)
            if getattr(leaf, "is_fully_addressable", True):
                return np.asarray(leaf)
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(
                leaf, tiled=True))

        flat = jax.tree_util.tree_flatten_with_path(tr.params)[0]
        params_path = os.path.join(
            info.service_dir,
            f"params_gen{info.generation}_rank{info.rank}.npz")
        np.savez(params_path, **{
            "/".join(str(getattr(k, "key", k)) for k in path):
                host_full(leaf) for path, leaf in flat})
        # the result write is the train→deployment-plane handoff: when a
        # publisher is listening (ENV_PUBLISH_FENCE) and the tracer is
        # on, bracket it in the publish-fence span — the supervisor's
        # Publisher brackets its read+gate+publish in the same span, so
        # the two processes' fleet exports stitch into one flow
        fence_cm = contextlib.nullcontext()
        if os.environ.get(ENV_PUBLISH_FENCE) and _obs_rt._enabled:
            from mmlspark_tpu.obs.spans import span as _obs_span
            from mmlspark_tpu.lifecycle.publish import PUBLISH_FENCE_SPAN
            fence_cm = _obs_span(PUBLISH_FENCE_SPAN, "lifecycle")
        with fence_cm:
            _atomic_write_json(info.result_path(), {
                "rank": info.rank, "world": info.world,
                "generation": info.generation, "devices": n_dev,
                "mesh": {a: int(s) for a, s in
                         zip(mesh.axis_names, mesh.devices.shape)},
                "steps": steps,
                "resumed": steps - len(tr.history),
                "history": [float(v) for v in tr.history],
                "params_npz": params_path,
            })
    return 0

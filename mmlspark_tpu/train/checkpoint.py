"""Mid-training checkpoint / resume for the train state.

The reference has NO API-level mid-training checkpointing: CNTK's own epoch
checkpoints land in its output dir but cannot be resumed through
``CNTKLearner`` (SURVEY §5; reference:
cntk-train/src/main/scala/CNTKLearner.scala:152-161 only reads the final
model). This subsystem goes beyond parity deliberately — on preemptible TPU
pods, resumable state is the failure-recovery story (job-level restart +
restore replaces elastic MPI rings).

State = a pure pytree {params, opt_state, step}; storage = Orbax
(tensorstore-backed, async-capable, multi-host-aware). A manifest tracks
steps so ``latest_step``/``max_to_keep`` work without globbing internals.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any

import numpy as np


class TrainCheckpointer:
    """Save/restore train-state pytrees under ``directory/step_<n>/``."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        if max_to_keep < 1:
            raise ValueError(
                f"max_to_keep must be >= 1, got {max_to_keep} (the pruning "
                "loop would delete the checkpoint just written)")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)

    # -- manifest --

    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, "manifest.json")

    def _read_manifest(self) -> dict[str, Any]:
        if not os.path.exists(self._manifest_path):
            return {"steps": []}
        with open(self._manifest_path) as f:
            return json.load(f)

    def _write_manifest(self, m: dict[str, Any]) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(m, f)
        os.replace(tmp, self._manifest_path)

    def steps(self) -> list[int]:
        return sorted(self._read_manifest()["steps"])

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    # -- save/restore --

    def fingerprint(self) -> dict[str, Any] | None:
        """The training-schedule fingerprint recorded at save time (or None
        for checkpoints written before one was recorded)."""
        return self._read_manifest().get("fingerprint")

    def save(self, state: Any, step: int | None = None,
             fingerprint: dict[str, Any] | None = None) -> int:
        import jax
        import orbax.checkpoint as ocp

        if step is None:
            step = int(np.asarray(state["step"]))
        path = self._step_dir(step)
        # multi-host: every process calls save() (Orbax coordinates the
        # collective write), but file-tree mutations outside Orbax —
        # clearing a stale dir, the manifest, pruning — are primary-only,
        # so a worker that dies mid-save can never leave the manifest
        # pointing at an uncommitted checkpoint (the manifest updates
        # strictly AFTER the barriered Orbax save completes everywhere)
        primary = jax.process_index() == 0
        if primary and os.path.exists(path):
            shutil.rmtree(path)
        if jax.process_count() > 1:
            # barrier: non-primary processes must not enter Orbax's own
            # destination-exists check while the primary is still clearing
            # a stale dir (a crashed run's partial save being overwritten)
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                f"mmlspark_tpu_ckpt_clear_{step}")
        # pass device arrays straight to Orbax: sharded jax.Arrays are saved
        # shard-per-host (no all-gather, multi-host safe); numpy passes
        # through unchanged
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state)
        ckptr.wait_until_finished()
        if primary:
            m = self._read_manifest()
            if fingerprint is not None:
                m["fingerprint"] = fingerprint
            if step not in m["steps"]:
                m["steps"].append(step)
            m["steps"].sort()
            while len(m["steps"]) > self.max_to_keep:
                old = m["steps"].pop(0)
                shutil.rmtree(self._step_dir(old), ignore_errors=True)
            self._write_manifest(m)
        return step

    def restore(self, step: int | None = None,
                target: Any = None) -> Any:
        """Restore a state pytree. ``target`` (a matching pytree) guides
        structure/dtypes AND shardings: each leaf restores directly to the
        target leaf's sharding (sharded restore, no host round-trip).
        Without a target the raw tree is returned as host arrays."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        path = self._step_dir(step)
        ckptr = ocp.StandardCheckpointer()
        if target is not None:
            import jax

            def abstract(leaf):
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    return jax.ShapeDtypeStruct(
                        leaf.shape, leaf.dtype,
                        sharding=getattr(leaf, "sharding", None))
                return leaf

            return ckptr.restore(path,
                                 jax.tree_util.tree_map(abstract, target))
        return ckptr.restore(path)

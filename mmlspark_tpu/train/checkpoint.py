"""Mid-training checkpoint / resume for the train state.

The reference has NO API-level mid-training checkpointing: CNTK's own epoch
checkpoints land in its output dir but cannot be resumed through
``CNTKLearner`` (SURVEY §5; reference:
cntk-train/src/main/scala/CNTKLearner.scala:152-161 only reads the final
model). This subsystem goes beyond parity deliberately — on preemptible TPU
pods, resumable state is the failure-recovery story (job-level restart +
restore replaces elastic MPI rings).

State = a pure pytree {params, opt_state, step}; storage = Orbax
(tensorstore-backed, async-capable, multi-host-aware). A manifest tracks
steps so ``latest_step``/``max_to_keep`` work without globbing internals.

Integrity: every saved step records a content digest in the manifest; a
restore validates it, and a torn/corrupt step directory (a worker killed
mid-write, a truncated leaf file) falls back to the previous manifest
step with a typed :class:`CheckpointCorruptError` event instead of
crashing the recovery that needed the checkpoint most. Pruning rewrites
the manifest BEFORE deleting directories, so a crash mid-GC leaves a
restorable manifest (orphan directories are swept on the next save).

Elastic topology change: :func:`reshard_state` re-places a live state
pytree onto a different mesh (the in-process path), and a checkpoint
restored with a target built on the NEW mesh reshards on read (the
cross-process path the training service supervisor uses — every leaf
restores straight to the new topology's shardings).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import event as _obs_event

_log = get_logger(__name__)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint step directory failed integrity validation (missing
    dir, truncated/altered leaf file, digest mismatch). Carries the step
    and reason so recovery tooling can report WHICH checkpoint was torn
    without re-probing the tree."""

    def __init__(self, directory: str, step: int | None, reason: str):
        self.directory = directory
        self.step = step
        self.reason = reason
        super().__init__(
            f"checkpoint step {step} under {directory} is corrupt: "
            f"{reason}")


def _dir_digest(path: str) -> str:
    """sha256 over the step directory's file tree: sorted relative paths,
    sizes, and contents. Any torn write — a truncated leaf, a missing
    shard file, a renamed dir entry — changes the digest.

    Cost note: this re-reads the step tree once at save (primary only)
    and once per validated restore (primary only in multi-host — the
    consensus path broadcasts the verdict). For checkpoints where a full
    re-read per save is too expensive, the right evolution is hashing
    shards as they stream out; the manifest format (``digests[step]``)
    already accommodates any digest definition."""
    h = hashlib.sha256()
    # sorted() exhausts the walk before hashing, so ordering comes from
    # sorting the (root, dirs, files) tuples by root path
    for root, _dirs, files in sorted(os.walk(path)):
        for name in sorted(files):
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, path)
            h.update(rel.encode())
            h.update(str(os.path.getsize(fp)).encode())
            with open(fp, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
    return h.hexdigest()


class TrainCheckpointer:
    """Save/restore train-state pytrees under ``directory/step_<n>/``."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        if max_to_keep < 1:
            raise ValueError(
                f"max_to_keep must be >= 1, got {max_to_keep} (the pruning "
                "loop would delete the checkpoint just written)")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)

    # -- manifest --

    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, "manifest.json")

    def _read_manifest(self) -> dict[str, Any]:
        if not os.path.exists(self._manifest_path):
            return {"steps": []}
        with open(self._manifest_path) as f:
            return json.load(f)

    def _write_manifest(self, m: dict[str, Any]) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(m, f)
        os.replace(tmp, self._manifest_path)

    def steps(self) -> list[int]:
        return sorted(self._read_manifest()["steps"])

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    # -- integrity --

    def verify_step(self, step: int) -> str | None:
        """Validate one step against its recorded digest; returns None
        when intact, else the human-readable corruption reason. Steps
        saved before digests were recorded (no manifest entry) validate
        as intact-if-present — the pre-digest behavior."""
        path = self._step_dir(step)
        if not os.path.isdir(path):
            return "step directory is missing"
        recorded = self._read_manifest().get("digests", {}).get(str(step))
        if recorded is None:
            return None
        actual = _dir_digest(path)
        if actual != recorded:
            return (f"content digest mismatch (recorded "
                    f"{recorded[:12]}…, got {actual[:12]}…)")
        return None

    def _record_corrupt(self, step: int, reason: str) -> None:
        _log.warning("checkpoint step %d under %s is corrupt (%s); "
                     "falling back to the previous manifest step",
                     step, self.directory, reason)
        if _obs_rt._enabled:
            _obs_registry().counter("train.checkpoint_corrupt").add()
            _obs_event("train/checkpoint_corrupt", "train",
                       {"directory": self.directory, "step": int(step),
                        "reason": reason})

    # -- save/restore --

    def fingerprint(self) -> dict[str, Any] | None:
        """The training-schedule fingerprint recorded at save time (or None
        for checkpoints written before one was recorded)."""
        return self._read_manifest().get("fingerprint")

    def save(self, state: Any, step: int | None = None,
             fingerprint: dict[str, Any] | None = None) -> int:
        import jax
        import orbax.checkpoint as ocp

        if step is None:
            step = int(np.asarray(state["step"]))
        path = self._step_dir(step)
        # multi-host: every process calls save() (Orbax coordinates the
        # collective write), but file-tree mutations outside Orbax —
        # clearing a stale dir, the manifest, pruning — are primary-only,
        # so a worker that dies mid-save can never leave the manifest
        # pointing at an uncommitted checkpoint (the manifest updates
        # strictly AFTER the barriered Orbax save completes everywhere)
        primary = jax.process_index() == 0
        if primary and os.path.exists(path):
            shutil.rmtree(path)
        if jax.process_count() > 1:
            # barrier: non-primary processes must not enter Orbax's own
            # destination-exists check while the primary is still clearing
            # a stale dir (a crashed run's partial save being overwritten)
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                f"mmlspark_tpu_ckpt_clear_{step}")
        # pass device arrays straight to Orbax: sharded jax.Arrays are saved
        # shard-per-host (no all-gather, multi-host safe); numpy passes
        # through unchanged
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state)
        ckptr.wait_until_finished()
        if primary:
            m = self._read_manifest()
            if fingerprint is not None:
                m["fingerprint"] = fingerprint
            if step not in m["steps"]:
                m["steps"].append(step)
            m["steps"].sort()
            # the torn-save detector: a digest over the committed tree,
            # recorded in the manifest the restore path validates against
            m.setdefault("digests", {})[str(step)] = _dir_digest(path)
            # crash-safe pruning: commit the manifest WITHOUT the dropped
            # steps FIRST, then delete — dying between the two leaves
            # orphan directories (swept below on the next save), never a
            # manifest pointing at deleted checkpoints
            drop = []
            while len(m["steps"]) > self.max_to_keep:
                old = m["steps"].pop(0)
                m["digests"].pop(str(old), None)
                drop.append(old)
            self._write_manifest(m)
            for old in drop:
                shutil.rmtree(self._step_dir(old), ignore_errors=True)
            self._sweep_orphans(m)
        return step

    def _sweep_orphans(self, m: dict[str, Any]) -> None:
        """Delete ``step_*`` dirs the manifest no longer references —
        the leftovers of a crash between manifest rewrite and delete."""
        keep = {f"step_{s}" for s in m["steps"]}
        try:
            entries = os.listdir(self.directory)
        except OSError:  # pragma: no cover - directory vanished
            return
        for name in entries:
            if name.startswith("step_") and name not in keep:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def restore(self, step: int | None = None,
                target: Any = None) -> Any:
        """Restore a state pytree. ``target`` (a matching pytree) guides
        structure/dtypes AND shardings: each leaf restores directly to the
        target leaf's sharding (sharded restore, no host round-trip) —
        including shardings on a DIFFERENT mesh than the save ran on,
        which is how elastic recovery reshards onto a new topology.

        With ``step=None`` (the recovery path), integrity validates the
        latest manifest step first and falls back to the previous one on
        corruption (typed ``train/checkpoint_corrupt`` event + counter);
        only when EVERY manifest step is torn does the typed
        :class:`CheckpointCorruptError` propagate. An explicitly
        requested ``step`` never falls back — a caller naming a step
        wants that step or a loud error."""
        explicit = step is not None
        if not explicit:
            step = self._choose_step_consensus()
        else:
            why = self.verify_step(step)
            if why is not None:
                raise CheckpointCorruptError(self.directory, step, why)
        return self._restore_step(step, target)

    def _choose_step(self) -> int:
        """The newest manifest step that passes digest validation;
        raises on none at all / all torn."""
        steps = self.steps()
        if not steps:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        reasons: list[str] = []
        for cand in reversed(steps):
            why = self.verify_step(cand)
            if why is None:
                return cand
            self._record_corrupt(cand, why)
            reasons.append(f"step {cand}: {why}")
        raise CheckpointCorruptError(
            self.directory, None,
            "every manifest step failed validation ("
            + "; ".join(reasons) + ")")

    def _choose_step_consensus(self) -> int:
        """Multi-host: the fallback step is chosen on the PRIMARY and
        broadcast, mirroring ``save``'s primary-only manifest
        discipline — per-process validation over a shared filesystem
        with attribute-caching skew (NFS) could pick DIFFERENT surviving
        steps on different hosts, and ranks entering the collective
        program with states from different steps is silent training
        corruption in exactly the recovery path this exists for. Also
        keeps the full-tree digest read O(bytes), not O(world×bytes).
        Single-process: just the local choice."""
        import jax

        if jax.process_count() <= 1:
            return self._choose_step()
        from jax.experimental import multihost_utils
        chosen, primary_exc = -1, None
        if jax.process_index() == 0:
            try:
                chosen = self._choose_step()
            except (FileNotFoundError, CheckpointCorruptError) as e:
                primary_exc = e  # cached: re-walking would double-fire
                #                  the corrupt events/counters and the
                #                  O(bytes) digest sweep
        agreed = int(np.asarray(multihost_utils.broadcast_one_to_all(
            np.asarray(chosen, np.int32))))
        if agreed < 0:
            if primary_exc is not None:
                raise primary_exc
            raise CheckpointCorruptError(
                self.directory, None,
                "primary found no restorable manifest step")
        return agreed

    def _restore_step(self, step: int, target: Any) -> Any:
        import orbax.checkpoint as ocp

        path = self._step_dir(step)
        ckptr = ocp.StandardCheckpointer()
        if target is not None:
            import jax

            def abstract(leaf):
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    return jax.ShapeDtypeStruct(
                        leaf.shape, leaf.dtype,
                        sharding=getattr(leaf, "sharding", None))
                return leaf

            return ckptr.restore(path,
                                 jax.tree_util.tree_map(abstract, target))
        return ckptr.restore(path)


def reshard_state(state: Any, old_mesh: Any, new_mesh: Any,
                  rules: Any = None, like: Any = None) -> Any:
    """Re-place a live train-state pytree from ``old_mesh`` onto
    ``new_mesh`` — the in-process half of elastic re-scale (a surviving
    process re-forming its mesh after losing devices; the cross-process
    half goes through a checkpoint restored with new-mesh targets).

    Placement targets come from ``like`` (a reference state already on
    ``new_mesh`` — e.g. a fresh ``Trainer.init_state``, byte-exact with
    init placement) when given, else from
    :func:`mmlspark_tpu.parallel.mesh.state_shardings` (``rules`` =
    the module's ``param_rules`` for structurally special params).
    Values are bit-preserved: each leaf round-trips through host memory
    and lands under the new topology's shardings.

    Requires every leaf to be fully addressable from this process (true
    in-process; a multi-host global array is not — there, save +
    restore-on-the-new-topology is the supported reshard path).
    ``old_mesh`` is the provenance check: a state whose leaves live on
    devices outside the mesh the caller believes it came from is flagged
    loudly (the caller is probably resharding the WRONG trainer's
    state), and the old→new transition is logged.
    """
    import jax
    from jax.sharding import NamedSharding

    from mmlspark_tpu.parallel import mesh as mesh_lib

    if old_mesh is not None:
        old_ids = {d.id for d in old_mesh.devices.reshape(-1)}
        for leaf in jax.tree_util.tree_leaves(state):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                leaf_ids = {d.id for d in sh.mesh.devices.reshape(-1)}
                if not leaf_ids <= old_ids:
                    _log.warning(
                        "reshard_state: state leaves live on devices %s "
                        "outside the declared old mesh %s — resharding "
                        "a different trainer's state?",
                        sorted(leaf_ids - old_ids), sorted(old_ids))
                break  # one committed leaf answers for the tree
        _log.info(
            "reshard_state: %s -> %s",
            dict(zip(old_mesh.axis_names, old_mesh.devices.shape)),
            dict(zip(new_mesh.axis_names, new_mesh.devices.shape)))

    targets = (jax.tree_util.tree_map(
        lambda leaf: getattr(leaf, "sharding", leaf), like)
        if like is not None
        else mesh_lib.state_shardings(new_mesh, state, rules=rules))

    def move(leaf, target):
        if not hasattr(leaf, "shape"):
            return leaf
        if (hasattr(leaf, "is_fully_addressable")
                and not leaf.is_fully_addressable):
            raise ValueError(
                "reshard_state needs fully-addressable leaves; a "
                "multi-host global array reshards through "
                "TrainCheckpointer.save + restore on the new topology")
        return jax.device_put(np.asarray(leaf), target)

    return jax.tree_util.tree_map(move, state, targets)

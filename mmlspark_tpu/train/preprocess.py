"""On-device train preprocessing — the :class:`DevicePreprocess` spec the
jitted step fuses.

The reference pipeline (OpenCV ``ImageTransformer`` + in-reader
``Imgcodecs.imdecode``) does all image work host-side, and until round 10
our train path mirrored it: ``data/readers.py`` decoded (and optionally
resized) on a host thread pool and every pixel crossed the tunnel at
final-batch width. Round 3 proved transfer bytes are the lever (uint8
shipping = 4× fewer H2D bytes); this module moves the REST of the image
work — resize, crop, flip, brightness/contrast, normalization — inside
the compiled train step, generalizing the round-3 in-step
``input_scale`` cast:

* **thin wire**: the loader ships source-resolution (or minimal
  crop-envelope — :func:`envelope_batch`) uint8 batches; geometry and
  normalization replay on device, where the VPU hides them under the
  matmuls;
* **one program**: the spec's ops trace into the SAME jitted step —
  zero extra dispatches, zero extra H2D/D2H crossings;
* **deterministic randomness**: every stochastic op draws from a key
  folded from the GLOBAL STEP (``fold_in(PRNGKey(cfg.seed), step)``
  where ``step`` is the device step counter carried in the train state),
  so prefetch on/off, host count, and resume-from-checkpoint all replay
  the identical augmentation stream bit-for-bit — the step counter is
  checkpointed, so a resumed run continues the stream exactly where the
  interrupted run left it.

Stage order (fixed; ``apply`` is the one implementation):

1. **geometry** — random source crop (``src_crop``) + bilinear
   ``resize``, fused with the normalize cast in one pass
   (:func:`mmlspark_tpu.ops.pallas.fused_resize_norm`: Pallas kernel or
   pure-XLA reference, selected by ``impl`` — the per-backend flag);
2. **normalize** — float32 × ``input_scale`` (inside the fused pass);
3. **stochastic augment** — pad+random-crop / flips / brightness /
   contrast (:func:`mmlspark_tpu.ops.augment.augment_batch`, operating
   on normalized floats);
4. **standardize** — optional per-channel ``(x - mean) / std``.

**The float-input convention** (the host-baseline A/B): uint8 input
takes the full chain; float input is taken as *already host-preprocessed
through stage 2* (:func:`host_preprocess` is the exact host twin of
stages 1–2), so only stages 3–4 run on device. Both wire forms therefore
see identical stochastic draws and identical post-normalize values —
the loss-parity contract ``tools/perf_smoke.py
check_train_device_preprocess`` gates in tier-1.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

IMPLS = ("auto", "xla", "pallas")


@dataclasses.dataclass(frozen=True)
class DevicePreprocess:
    """Declarative on-device preprocessing spec, fused into the jitted
    train step by ``TrainConfig(preprocess=...)``.

    Geometry fields (``src_crop``, ``resize``) consume the thin uint8
    wire form; stochastic fields mirror
    :mod:`mmlspark_tpu.ops.augment` (values in the NORMALIZED scale —
    ``brightness=0.1`` shifts [0, 1]-scaled pixels); ``mean``/``std``
    standardize per channel after augmentation. ``impl`` selects the
    fused-geometry backend: ``auto`` (Pallas on TPU, XLA elsewhere),
    ``xla``, or ``pallas`` (interpret-mode on CPU)."""

    resize: tuple | None = None      # (oh, ow) bilinear target
    src_crop: tuple | None = None    # (ch, cw) random source window
    crop_pad: int = 0                # post-resize reflect pad + random crop
    flip_lr: bool = False
    flip_ud: bool = False
    brightness: float = 0.0          # uniform shift in [-b, b], normalized
    contrast: tuple | None = None    # (lo, hi) per-sample contrast factor
    mean: tuple | None = None        # per-channel, normalized scale
    std: tuple | None = None
    impl: str = "auto"               # auto | xla | pallas

    def __post_init__(self):
        for field in ("resize", "src_crop", "contrast", "mean", "std"):
            v = getattr(self, field)
            if v is not None:
                object.__setattr__(self, field, tuple(v))
        for field in ("resize", "src_crop"):
            v = getattr(self, field)
            if v is not None and (len(v) != 2 or min(v) < 1):
                raise ValueError(f"DevicePreprocess.{field} must be a "
                                 f"(height, width) pair >= 1, got {v!r}")
        if self.contrast is not None and (
                len(self.contrast) != 2
                or not 0 <= self.contrast[0] <= self.contrast[1]):
            raise ValueError("DevicePreprocess.contrast must be a "
                             f"0 <= lo <= hi pair, got {self.contrast!r}")
        if self.crop_pad < 0:
            raise ValueError(
                f"DevicePreprocess.crop_pad must be >= 0, "
                f"got {self.crop_pad}")
        if self.std is not None and any(s == 0 for s in self.std):
            raise ValueError("DevicePreprocess.std contains a zero "
                             f"channel: {self.std!r}")
        if self.impl not in IMPLS:
            raise ValueError(f"DevicePreprocess.impl must be one of "
                             f"{IMPLS}, got {self.impl!r}")

    # ---- construction / identity ----

    @classmethod
    def parse(cls, obj: Any) -> "DevicePreprocess | None":
        """None / spec / plain-dict (the TrainConfig wire form) → spec."""
        if obj is None or isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            return cls(**obj)
        raise TypeError(
            "TrainConfig.preprocess must be a DevicePreprocess, a dict of "
            f"its fields, or None; got {type(obj).__name__}")

    def fingerprint(self) -> str:
        """Canonical string identity for the checkpoint-schedule
        fingerprint: resuming under a CHANGED spec would silently replay
        different pixels into the remaining steps."""
        d = dataclasses.asdict(self)
        return ",".join(f"{k}={d[k]!r}" for k in sorted(d))

    # ---- static geometry replay (the analyzer's infer_schema) ----

    def out_shape(self, in_shape: tuple) -> tuple:
        """Replay the spec over an ``(h, w, c)`` input geometry; raises
        ``ValueError`` on a geometry the device chain would reject —
        the pre-flight half of ``analysis.audit_train_preprocess``."""
        if len(in_shape) != 3:
            raise ValueError(
                f"DevicePreprocess expects (h, w, c) image geometry, "
                f"got {tuple(in_shape)}")
        h, w, c = (int(d) for d in in_shape)
        if self.src_crop is not None:
            ch, cw = self.src_crop
            if ch > h or cw > w:
                raise ValueError(
                    f"src_crop {self.src_crop} larger than the source "
                    f"image ({h}, {w})")
            h, w = ch, cw
        if self.resize is not None:
            h, w = self.resize
        if self.crop_pad and self.crop_pad > min(h, w) - 1:
            raise ValueError(
                f"crop_pad {self.crop_pad} needs reflect padding wider "
                f"than the {h}x{w} image allows (max {min(h, w) - 1})")
        for field in ("mean", "std"):
            v = getattr(self, field)
            if v is not None and len(v) not in (1, c):
                raise ValueError(
                    f"{field} has {len(v)} channels for {c}-channel "
                    "images")
        return h, w, c


def resolve(obj: Any) -> DevicePreprocess | None:
    """``TrainConfig.preprocess`` (spec | dict | None) → validated spec."""
    return DevicePreprocess.parse(obj)


def _geometry_normalize(spec: DevicePreprocess, key, x, scale):
    """Stages 1–2 on the thin uint8 wire form: random source crop +
    bilinear resize + f32 × scale, as ONE fused pass."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.ops.pallas.resize import fused_resize_norm

    n, h, w, _c = x.shape
    if spec.src_crop is not None:
        ch, cw = spec.src_crop
        ky, kx = jax.random.split(key)
        oy = jax.random.randint(ky, (n,), 0, h - ch + 1, dtype=jnp.int32)
        ox = jax.random.randint(kx, (n,), 0, w - cw + 1, dtype=jnp.int32)
    else:
        ch, cw = h, w
        oy = ox = jnp.zeros((n,), jnp.int32)
    out_hw = spec.resize or (ch, cw)
    if spec.src_crop is None and tuple(out_hw) == (h, w):
        # identity geometry: the fused pass degenerates to the round-3
        # cast convention exactly (v00 × 1 = v00) — skip the gathers
        return x.astype(jnp.float32) * np.float32(scale)
    return fused_resize_norm(x, oy, ox, (ch, cw), out_hw, scale,
                             impl=spec.impl)


def apply(spec: DevicePreprocess, key, x, scale: float):
    """The in-step entry: full chain for uint8 input, stages 3–4 only for
    float input (already host-preprocessed — see the module docstring's
    float-input convention). Pure jax; traces into the step program."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.ops import augment

    k_geom, k_aug = jax.random.split(key)
    if x.dtype == jnp.uint8:
        x = _geometry_normalize(spec, k_geom, x, scale)
    else:
        x = x.astype(jnp.float32)
    x = augment.augment_batch(
        k_aug, x, flip_lr=spec.flip_lr, flip_ud=spec.flip_ud,
        crop_pad=spec.crop_pad, brightness=spec.brightness,
        contrast=spec.contrast)
    if spec.mean is not None or spec.std is not None:
        if spec.mean is not None:
            x = x - jnp.asarray(spec.mean, jnp.float32)
        if spec.std is not None:
            x = x / jnp.asarray(spec.std, jnp.float32)
    # the batch is data, not a differentiation target: make that explicit
    # so no backward rule is ever required of the fused kernel
    return jax.lax.stop_gradient(x)


def host_preprocess(spec: DevicePreprocess, batch: np.ndarray,
                    scale: float) -> np.ndarray:
    """The exact host twin of stages 1–2 (numpy): deterministic geometry
    (``resize``) + the normalize cast. This is the HOST-PREPROCESS
    baseline wire form of the thin-wire A/B — feed its float output to a
    Trainer carrying the same spec and the device applies only the
    stochastic stages, with identical draws. Random source crops cannot
    be replayed host-side (the draw lives in the step): specs with
    ``src_crop`` have no host baseline."""
    from mmlspark_tpu.ops.pallas.resize import fused_resize_norm_host

    if spec.src_crop is not None:
        raise ValueError(
            "host_preprocess cannot replay a random src_crop — the draw "
            "happens inside the jitted step; drop src_crop from the "
            "host-baseline spec")
    x = np.asarray(batch)
    if x.ndim != 4:
        raise ValueError(
            f"host_preprocess expects an [N, H, W, C] batch, got shape "
            f"{x.shape}")
    n, h, w, _c = x.shape
    if spec.resize is not None and tuple(spec.resize) != (h, w):
        zeros = np.zeros(n, np.int32)
        return fused_resize_norm_host(x, zeros, zeros, (h, w),
                                      spec.resize, scale)
    return x.astype(np.float32) * np.float32(scale)


def envelope_batch(images: list, envelope: tuple) -> np.ndarray:
    """Pack ragged source-resolution HWC uint8 images into ONE
    ``[N, H, W, C]`` batch by zero-pad / center-crop only — no
    interpolation, pure memcpy — the minimal crop-envelope wire format
    for thin-wire streaming of mixed-resolution sources. Larger images
    center-crop to the envelope, smaller ones center inside zero
    padding; the device spec replays the real geometry (crop + resize)
    from there."""
    h, w = int(envelope[0]), int(envelope[1])
    if not images:
        return np.zeros((0, h, w, 3), np.uint8)
    arrs = []
    for img in images:
        a = np.asarray(img)
        if a.dtype != np.uint8:
            # the envelope IS the thin uint8 wire form — silently
            # truncating normalized floats into it would ship all-black
            # batches; refuse loudly instead
            raise TypeError(
                f"envelope_batch packs the uint8 wire form; got dtype "
                f"{a.dtype} (host-preprocessed float batches skip the "
                "envelope and ship as-is)")
        if a.ndim == 2:
            a = a[:, :, None]
        arrs.append(a)
    c = max(a.shape[2] for a in arrs)
    out = np.zeros((len(arrs), h, w, c), np.uint8)
    for i, a in enumerate(arrs):
        sh, sw = a.shape[:2]
        # crop (centered) when the source overflows the envelope
        cy, cx = max((sh - h) // 2, 0), max((sw - w) // 2, 0)
        a = a[cy:cy + h, cx:cx + w]
        sh, sw = a.shape[:2]
        # center (zero pad) when it underflows
        oy, ox = (h - sh) // 2, (w - sw) // 2
        out[i, oy:oy + sh, ox:ox + sw, :a.shape[2]] = a
    return out

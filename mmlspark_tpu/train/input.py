"""Asynchronous prefetching train-input pipeline.

The training loop used to commit every batch synchronously inside the
step loop: gather the shuffled batch, ``device_put`` it, run the step —
so through a transfer-bound link the upload of batch i+1 serialized
behind the compute of batch i. :class:`DeviceLoader` is the standard
overlapped input pipeline (tf.data's prefetch, Murray et al. VLDB 2021;
the ``prefetch_to_device`` double-buffering idiom of the Flax training
playbook) built as a first-class subsystem:

* **batch assembly** (permutation gather / chunk-rebatch / image decode)
  runs on ONE background thread pulling the host-batch iterator,
* the **commit** (``jax.device_put`` or
  ``jax.make_array_from_process_local_data``, reusing the Trainer's data
  shardings) is issued up to ``depth`` batches ahead of consumption, so
  steady-state wall clock per step is max(H2D, compute) instead of the
  sum,
* HBM held by in-flight batches is bounded by the queue depth,
* the consumer pulls already-device-resident arrays and raises the
  producer's exception (source or commit) at the point of consumption;
  ``close()`` shuts the worker down without leaking the thread even when
  the consumer abandons the loop mid-epoch.

``depth=0`` is the synchronous fallback: the same iterator/commit are
driven inline with identical numerics (this is the A/B path ``bench.py``
measures). Prefetching never changes numerics at any depth — the same
host batches are committed to the same shardings in the same order; only
*when* the H2D transfer is issued moves.

Multi-host rule (docs/training_input.md): a producer whose iterator
performs cross-process exchanges (the ``fit_stream`` liveness allgather /
batch-signature sync) must call :meth:`DeviceLoader.drain_barrier` first,
so every process interleaves collectives with step dispatch in the same
order; the consumer reports step dispatches via
:meth:`DeviceLoader.note_dispatched`.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs import flight as _obs_flight
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import span as _annotate

_log = get_logger(__name__)

THREAD_PREFIX = "DeviceLoader"

_ITEM, _ERROR, _DONE = "item", "error", "done"


def _item_nbytes(item: Any) -> int:
    """Host bytes of the numpy payload inside one (possibly tagged)
    batch item — the WIRE size the commit is about to ship. Non-array
    leaves (step tags, chunk indices) count zero."""
    nbytes = getattr(item, "nbytes", None)
    if nbytes is not None and hasattr(item, "dtype"):
        return int(nbytes)
    if isinstance(item, (tuple, list)):
        return sum(_item_nbytes(v) for v in item)
    return 0

# loader spans go through the obs tracer (obs.span): disabled they are a
# flag check; enabled they land in the ring buffer, and with
# obs.enable(device_annotations=True) they ALSO enter
# jax.profiler.TraceAnnotation — the pre-obs behavior, now opt-in


class DeviceLoader:
    """Bounded-queue prefetching loader: iterate committed device batches.

    Parameters
    ----------
    source:
        Iterator/iterable of host-side items (typically
        ``(bx, by, bw)`` numpy batches, or tagged tuples around them).
    commit:
        ``item -> item`` mapping host arrays to device-committed arrays
        (``jax.device_put`` / ``make_array_from_process_local_data`` with
        the trainer's data sharding). Runs on the worker thread, up to
        ``depth`` items ahead of consumption.
    depth:
        Maximum committed-but-unconsumed batches (queue bound = HBM
        bound). ``0`` disables the worker thread entirely: assembly and
        commit run inline in ``__next__`` (the synchronous A/B path).
    name:
        Label for the worker thread and profiler spans.

    Accounting (read after — or during — iteration):

    * ``committed`` / ``consumed`` — batches through each end,
    * ``max_ahead`` — max batches that were already committed *beyond*
      the one being consumed (the proof the pipeline actually ran ahead),
    * ``wait_s`` — consumer time blocked waiting for input (for
      ``depth=0`` this is the full inline assemble+commit time, so the
      number stays comparable across the A/B),
    * ``assemble_s`` / ``commit_s`` — producer-side decomposition.
    """

    def __init__(self, source: Iterable | Iterator,
                 commit: Callable[[Any], Any],
                 depth: int = 2, name: str = "train-input"):
        self.depth = max(int(depth), 0)
        self.name = name
        self._source = iter(source)
        self._commit = commit
        self.committed = 0
        self.consumed = 0
        self.dispatched = 0
        self.max_ahead = 0
        self.wait_s = 0.0
        self.assemble_s = 0.0
        self.commit_s = 0.0
        # total host bytes the commits shipped — the honest wire-format
        # observable of the thin-wire A/B (uint8 source pixels vs
        # host-preprocessed f32), independent of the device-side seam
        self.wire_bytes = 0
        self._done = False
        if self.depth > 0:
            self._q: queue.Queue = queue.Queue(maxsize=self.depth)
            self._stop = threading.Event()
            self._disp_cv = threading.Condition()
            self._thread = threading.Thread(
                target=self._run, name=f"{THREAD_PREFIX}[{name}]",
                daemon=True)
            self._thread.start()

    # ---- producer (worker thread) ----

    def _run(self) -> None:
        # flight-recorder heartbeat: armed for the worker's lifetime —
        # a producer stuck in assembly (a stalled stream source) or in
        # the device commit is a hang; waiting on a full queue is not
        # (self._put beats while it polls)
        hb = f"loader/{self.name}"
        rec = _obs_flight._rec
        if rec is not None:
            rec.arm(hb)
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    with _annotate(f"{self.name}/assemble", "train"):
                        item = next(self._source)
                except StopIteration:
                    break
                self.assemble_s += time.perf_counter() - t0
                self.wire_bytes += _item_nbytes(item)
                t0 = time.perf_counter()
                with _annotate(f"{self.name}/commit", "train"):
                    out = self._commit(item)
                self.commit_s += time.perf_counter() - t0
                self.committed += 1
                if _obs_flight._rec is not None:
                    _obs_flight._rec.beat(hb)
                if not self._put((_ITEM, out)):
                    return  # closed while blocked on a full queue
            self._put((_DONE, None))
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            self._put((_ERROR, e))
        finally:
            if _obs_flight._rec is not None:
                _obs_flight._rec.disarm(hb)

    def _put(self, msg: tuple) -> bool:
        """Bounded put that aborts when the loader is closed — a consumer
        that stopped pulling must never leave the worker blocked."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.05)
                return True
            except queue.Full:
                # waiting on the consumer is not a producer hang: keep
                # the flight heartbeat fresh while the queue is full
                if _obs_flight._rec is not None:
                    _obs_flight._rec.beat(f"loader/{self.name}")
                continue
        return False

    # ---- consumer ----

    def __iter__(self) -> "DeviceLoader":
        return self

    def __next__(self) -> Any:
        if self.depth == 0:
            # synchronous fallback: identical iterator + commit, inline.
            # The full assemble+commit time counts as input wait so the
            # prefetch on/off decomposition stays comparable
            t0 = time.perf_counter()
            with _annotate(f"{self.name}/input", "train"):
                item = next(self._source)  # StopIteration ends iteration
                self.assemble_s += time.perf_counter() - t0
                self.wire_bytes += _item_nbytes(item)
                t1 = time.perf_counter()
                out = self._commit(item)
                self.commit_s += time.perf_counter() - t1
            self.wait_s += time.perf_counter() - t0
            self.committed += 1
            self.consumed += 1
            return out
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        with _annotate(f"{self.name}/wait", "train"):
            tag, val = self._q.get()
        self.wait_s += time.perf_counter() - t0
        if tag is _DONE:
            self._done = True
            raise StopIteration
        if tag is _ERROR:
            self._done = True
            self.close()
            raise val
        # batches fully committed BEYOND the one now being handed over
        ahead = self.committed - self.consumed - 1
        if ahead > self.max_ahead:
            self.max_ahead = ahead
        self.consumed += 1
        return val

    # ---- multi-host dispatch fencing ----

    def note_dispatched(self) -> None:
        """Consumer: record that the step for the last pulled batch has
        been dispatched (required only when the producer uses
        :meth:`drain_barrier`)."""
        if self.depth == 0:
            return
        with self._disp_cv:
            self.dispatched += 1
            self._disp_cv.notify_all()

    def drain_barrier(self, poll_s: float = 0.05) -> None:
        """Producer: block until every committed batch's step has been
        dispatched by the consumer. Multi-host producers call this before
        issuing a cross-process collective (liveness allgather, batch
        signature sync) so every process's device-op issue order is
        identical — collectives interleaved differently across processes
        deadlock. Returns immediately in synchronous (depth=0) mode and
        when the loader is closed."""
        if self.depth == 0:
            return
        with self._disp_cv:
            while (not self._stop.is_set()
                   and self.dispatched < self.committed):
                self._disp_cv.wait(timeout=poll_s)

    # ---- lifecycle ----

    def close(self) -> None:
        """Stop the worker and release the queue. Idempotent; safe after
        consumer exceptions mid-epoch (no leaked thread, no deadlock)."""
        if self.depth == 0:
            close_fn = getattr(self._source, "close", None)
            if close_fn is not None:
                try:
                    close_fn()
                except Exception:  # pragma: no cover - best-effort
                    pass
            return
        self._stop.set()
        with self._disp_cv:
            self._disp_cv.notify_all()  # unblock a producer in the barrier
        try:  # unblock a producer stuck on a full queue
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10.0)
        if self._thread.is_alive():  # pragma: no cover - defensive
            _log.warning("DeviceLoader[%s] worker did not stop", self.name)
            return
        # deterministic release of source-held resources (decode pools,
        # file handles) instead of waiting for GC of the abandoned frame
        close_fn = getattr(self._source, "close", None)
        if close_fn is not None:
            try:
                close_fn()
            except Exception:  # pragma: no cover - best-effort
                pass

    def __enter__(self) -> "DeviceLoader":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def input_stats(loader: DeviceLoader, loop_s: float) -> dict:
    """Per-run input-wait vs. step-time accounting for a finished loop.

    ``input_bound_fraction`` is the share of loop wall-clock the consumer
    spent blocked on input — ~0 means compute-bound (prefetch hid the
    input side), ~1 means the pipeline is input-bound and a deeper queue
    or faster assembly/link is the lever. ``step_s`` is everything else
    in the consumer loop: step dispatch plus the periodic lagged metric
    fetches that drain the device pipeline."""
    wait = loader.wait_s
    loop_s = max(float(loop_s), 0.0)
    stats = {
        "prefetch_depth": loader.depth,
        "batches": loader.consumed,
        "committed_ahead_max": loader.max_ahead,
        "input_wait_s": round(wait, 4),
        "step_s": round(max(loop_s - wait, 0.0), 4),
        "input_bound_fraction": (round(min(wait / loop_s, 1.0), 4)
                                 if loop_s > 0 else 0.0),
        "assemble_s": round(loader.assemble_s, 4),
        "commit_s": round(loader.commit_s, 4),
        "wire_mb": round(loader.wire_bytes / 2 ** 20, 3),
    }
    if _obs_rt._enabled:
        # publish the same numbers into the process-wide registry (one
        # gauge per key, labeled by loader), so `Trainer.input_stats`
        # and the /metrics exporter read identical values — the "one
        # telemetry substrate" contract (docs/observability.md)
        reg = _obs_registry()
        for key, val in stats.items():
            reg.gauge(f"train.input.{key}", loader=loader.name).set(val)
        reg.counter("train.input.batches_total",
                    loader=loader.name).add(loader.consumed)
    return stats

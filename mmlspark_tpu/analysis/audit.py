"""Device-plan audit structures — the symbolic replay of the pipeline
planner's segmentation.

The audit answers, before any data moves: which stage runs will fuse into
one compiled program, where fusion breaks (and why), and how many
H2D uploads / D2H fetch rounds a transform over N rows will cost against
the one-per-minibatch contract. It reuses the planner's own segmentation
(``core/plan.collect_segment``) with the abstract
:meth:`~mmlspark_tpu.analysis.info.TableSchema.entry_meta` probe standing
in for the concrete table, so the predicted plan is the executed plan by
construction. Crossing arithmetic goes through
``core/plan.predict_segment_minibatches`` (the executor's dp-rounded
minibatch sizing) — nothing here compiles, uploads, or fetches.

The audit's **multi-chip mode** lives in
:mod:`mmlspark_tpu.analysis.spmd` (:func:`spmd_audit` below delegates):
the same symbolic segment replay, additionally verifying each fused
segment's SPMD behavior — entry batch sharded over the data axes,
minibatch walk divisible by the dp extent, and zero manual collectives
in the composite (inference relies on XLA-inserted resharding only).
See docs/spmd_analysis.md.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class PlanSegmentReport:
    """One executor step: a fused device run or a single host stage.

    ``out_dtypes`` carries the step's predicted per-column output dtypes
    — for device segments the eval_shape-traced truth (``ArrayMeta``
    dtypes the composite restores on emit, whatever the precision
    policy computes in); for host steps the schema-predicted dtype of
    each declared output. ``precision`` names the segment's resolved
    serving precision (``"f32"`` when no policy applies) and
    ``tolerance`` its expected max-abs parity bound vs the f32 offline
    transform (docs/quantization.md)."""

    kind: str                      # "device" | "host"
    start: int                     # first stage index (inclusive)
    end: int                       # last stage index (exclusive)
    stages: list                   # stage type names
    entry_col: str | None = None   # fused runs: the one uploaded column
    minibatches: int | None = None  # crossing rounds (None = not predictable)
    notes: list = dataclasses.field(default_factory=list)
    out_dtypes: dict = dataclasses.field(default_factory=dict)
    precision: str | None = None   # device segments: resolved policy mode
    tolerance: float | None = None  # expected parity bound for it

    def describe(self) -> str:
        names = "→".join(self.stages)
        head = f"[{self.start}:{self.end}] {self.kind}: {names}"
        if self.kind == "device":
            head += f" (entry {self.entry_col!r}"
            if self.minibatches is not None:
                head += f", {self.minibatches} minibatch round(s)"
            if self.precision is not None:
                head += f", precision {self.precision}"
                if self.tolerance is not None:
                    head += f" (expected parity ≤ {self.tolerance:g})"
            head += ")"
        elif self.minibatches:
            head += f" ({self.minibatches} minibatch round(s) on its own path)"
        if self.out_dtypes:
            cols = ", ".join(f"{c}:{d}" for c, d in self.out_dtypes.items())
            head += f" → {cols}"
        return head


@dataclasses.dataclass
class PlanAudit:
    """The predicted execution plan of one transform call.

    ``uploads``/``fetches`` are the predicted H2D / D2H crossing totals per
    transform over the audited row count — ``None`` when device work exists
    but the row count (or a stage's row effect) is unknown. A pipeline with
    no device work predicts 0 exactly, whatever the row count.
    """

    segments: list[PlanSegmentReport] = dataclasses.field(
        default_factory=list)
    uploads: int | None = 0
    fetches: int | None = 0

    @property
    def device_segments(self) -> list[PlanSegmentReport]:
        return [s for s in self.segments if s.kind == "device"]

    def structure(self) -> list[tuple[str, int]]:
        """``[(kind, n_stages), ...]`` — comparable to
        ``core/plan.describe_plan`` output shapes."""
        return [(s.kind, s.end - s.start) for s in self.segments]

    def format(self) -> str:
        lines = [s.describe() for s in self.segments]
        if self.uploads is None:
            lines.append("crossings: not statically predictable "
                         "(unknown row count or row-changing stage)")
        else:
            lines.append(f"crossings: {self.uploads} H2D upload(s), "
                         f"{self.fetches} D2H fetch round(s) predicted")
        return "\n".join(lines)


@dataclasses.dataclass
class TrainPreprocessAudit:
    """Pre-flight replay of a train-input ``DevicePreprocess`` spec —
    the train segment's face of the plan audit.

    ``infer_schema`` for the preprocess spec: the symbolic geometry walk
    (``DevicePreprocess.out_shape``) validates the spec against the
    source image geometry (out-of-bounds source crop, reflect padding
    wider than the image, channel-count mismatches on mean/std) BEFORE
    any batch is assembled, and the byte predictions price both wire
    forms of the thin-wire A/B per batch:

    * ``thin_bytes`` — source-resolution uint8 on the wire (geometry +
      normalize replayed in the jitted step);
    * ``host_bytes`` — the host-preprocess baseline: float32 at the
      POST-geometry width.

    The predictions are exact — ``tests/test_train_preprocess.py`` holds
    ``thin_bytes`` equal to the bytes the obs registry observes at the
    ``core/plan.train_commit`` seam per committed batch.
    """

    in_shape: tuple               # (h, w, c) source geometry
    out_shape: tuple              # (h, w, c) after geometry replay
    batch_size: int
    thin_bytes: int               # per-batch uint8 wire (x payload only)
    host_bytes: int               # per-batch f32 host-preprocess wire
    reduction: float              # host_bytes / thin_bytes

    def describe(self) -> str:
        return (f"train preprocess: {self.in_shape} uint8 → "
                f"{self.out_shape} f32 on device; wire "
                f"{self.thin_bytes} B/batch thin vs {self.host_bytes} B "
                f"host-preprocessed ({self.reduction:.2f}x reduction)")


def audit_train_preprocess(spec: Any, input_shape: tuple,
                           batch_size: int) -> TrainPreprocessAudit:
    """Statically validate a ``DevicePreprocess`` spec over a source
    image geometry and predict the per-batch H2D byte cost of both wire
    forms. Raises :class:`~mmlspark_tpu.analysis.info.SchemaError` on a
    geometry the device chain would reject at trace time."""
    import numpy as np

    from mmlspark_tpu.analysis.info import SchemaError
    from mmlspark_tpu.train.preprocess import DevicePreprocess

    spec = DevicePreprocess.parse(spec)
    if spec is None:
        raise SchemaError("preprocess-missing",
                          "audit_train_preprocess needs a spec; got None")
    try:
        out = spec.out_shape(tuple(input_shape))
    except ValueError as e:
        raise SchemaError("preprocess-geometry", str(e)) from e
    bs = int(batch_size)
    thin = bs * int(np.prod(input_shape))
    host = bs * int(np.prod(out)) * 4
    return TrainPreprocessAudit(
        in_shape=tuple(int(d) for d in input_shape),
        out_shape=tuple(out), batch_size=bs, thin_bytes=thin,
        host_bytes=host, reduction=round(host / thin, 4))


def spmd_audit(stages: list, meta_of: Any, n_rows: int | None = None):
    """The plan audit's multi-chip mode: delegate to
    :func:`mmlspark_tpu.analysis.spmd.audit_plan_spmd` (lazy import —
    the SPMD verifier pulls in jaxpr machinery this module's pure
    report types must not depend on)."""
    from mmlspark_tpu.analysis.spmd import audit_plan_spmd
    return audit_plan_spmd(stages, meta_of, n_rows=n_rows)


def standalone_crossings(stage: Any, schema: Any, n_rows: int | None
                         ) -> int | None:
    """Crossing rounds a stage costs when it runs OUTSIDE a fused segment
    (the host walk). Most host stages cost zero; a lone ``JaxModel`` runs
    its own minibatch pipeline, and an ``ImageFeaturizer`` executes its
    internal resize→forward plan. Returns None when the stage does device
    work but the count is not predictable."""
    from mmlspark_tpu.models.image_featurizer import ImageFeaturizer
    from mmlspark_tpu.models.jax_model import JaxModel

    if isinstance(stage, ImageFeaturizer):
        if stage.model is None:
            return 0
        from mmlspark_tpu.analysis.analyzer import analyze
        report = analyze(stage._stages(), schema, n_rows=n_rows)
        return report.plan.uploads if report.plan is not None else None
    if isinstance(stage, JaxModel):
        if stage.model is None or n_rows == 0:
            return 0
        if n_rows is None:
            return None
        from mmlspark_tpu.core import config, plan
        size = int(stage.minibatch_size
                   or config.get("default_minibatch_size"))
        size = plan.dp_rounded_minibatch(
            size, plan.mesh_dp(stage._mesh()), n_rows)
        return -(-n_rows // size)
    return 0

"""Whole-repo concurrency verifier — static lock-order / deadlock analysis.

The reference validated pipelines *before* execution (transformSchema
pre-flight), and we extended that discipline to device plans
(analysis/analyzer.py) and SPMD schedules (analysis/spmd.py).  This
module extends it to the layer where the review-hardening bugs of the
serve/train subsystems actually live: **threads and locks**.  It is a
pure-AST interprocedural pass (pyflakes-style — it never imports the
code it analyzes) that

* inventories every ``threading.Lock/RLock/Condition/Semaphore`` and
  every ``threading.Thread`` spawn site in the package,
* builds a call graph (import aliases, ``self.`` methods, attribute
  types inferred from annotations/constructor calls, unique-name
  fallbacks) and propagates *held-lock sets* through callees to a
  fixpoint,
* derives the **lock-order graph** — which lock identities can be held
  when another is acquired — through ``with`` blocks, manual
  acquire/release, and transitive calls,

and reports typed findings:

=======  ==============================================================
CC101    lock-order cycle (potential deadlock) — reported once per
         cycle with a witness path for *both* directions.
CC102    blocking operation while a lock is held: thread ``join``,
         ``queue.Queue`` get/put, ``subprocess`` waits, ``urlopen``,
         ``time.sleep``, ``Event.wait``, future ``.result()``,
         ``block_until_ready`` — the PR 9 signal-handler-deadlock
         class.  ``Condition.wait()`` on the *held* condition is
         exempt (it releases the lock while waiting).
CC103    manual ``acquire()`` whose release is not guaranteed by a
         dominating ``try/finally`` (both the ``acquire();
         try/finally`` and ``if acquire(blocking=False):
         try/finally`` idioms are accepted).
CC104    thread-lifecycle leak: a non-daemon ``Thread`` with no
         reachable ``join()`` owner.
CC105    callback/hook invoked while a lock is held (the
         flight-recorder excepthook class): user code running under an
         internal lock can re-enter and deadlock.
CC100    suppression hygiene: a ``# concurrency: allow(...)`` pragma
         with an empty justification (every suppression must document
         the invariant that makes the site safe).
=======  ==============================================================

Suppression policy (same shape as tools/lint_jax.py, but a
justification is *required*)::

    some_call()  # concurrency: allow(CC102): compile serialization is the point

``DEFAULT_ALLOWLIST`` carries the curated repo-level suppressions, each
with a non-empty per-entry justification; tests assert every entry
still suppresses a live finding.

The static graph is adversarially cross-checked at runtime by
:mod:`mmlspark_tpu.obs.lockwitness` (the instrumented-lock witness):
each static edge observed during the tier-1 serve burst is labeled
CONFIRMED, the rest stay PLAUSIBLE — the same posture the SPMD
verifier takes (predicted == lowered).  ``tools/analyze.py
concurrency`` is the CLI; ``check_concurrency_clean`` in
tools/perf_smoke.py is the tier-1 gate.  Rule catalogue and lock
inventory: docs/concurrency.md.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable

# ---------------------------------------------------------------------------
# rule catalogue

RULES = {
    "CC100": "suppression pragma with empty justification",
    "CC101": "lock-order cycle (potential deadlock)",
    "CC102": "blocking operation while a lock is held",
    "CC103": "manual acquire() without dominating try/finally release",
    "CC104": "non-daemon thread with no reachable join() owner",
    "CC105": "callback/hook invoked while a lock is held",
}

_PRAGMA_RE = re.compile(r"#\s*concurrency:\s*allow\(([A-Z0-9, ]+)\)(?::(.*))?")

# Curated repo-level suppressions: path suffix -> {rule: justification}.
# Every justification must be non-empty and every entry must suppress at
# least one live finding (tests/test_concurrency.py enforces both).
DEFAULT_ALLOWLIST: dict[str, dict[str, str]] = {}

# Blocking call roots (module-level functions) for CC102.
_BLOCKING_FUNCS = {
    ("time", "sleep"): "time.sleep",
    ("subprocess", "run"): "subprocess.run",
    ("subprocess", "call"): "subprocess.call",
    ("subprocess", "check_call"): "subprocess.check_call",
    ("subprocess", "check_output"): "subprocess.check_output",
    ("urllib.request", "urlopen"): "urlopen",
    ("socket", "create_connection"): "socket.create_connection",
}

# Method names that block regardless of receiver type.
_BLOCKING_ANY_METHOD = {
    "block_until_ready": "device fetch (block_until_ready)",
    "communicate": "subprocess communicate",
}

# Callback-ish names for CC105: calling one of these while a lock is
# held hands control to user code that may re-enter the lock.
_CALLBACK_NAME_RE = re.compile(
    r"(^on_[a-z0-9_]+$)|(_hook$)|(_hooks$)|(_callback$)|(_cb$)|(^callback$)|(^cb$)"
)

# Method names too generic for the unique-name call-graph fallback:
# `os.path.join`, `"".join`, `list.append`, `json.dump` etc. would
# otherwise resolve to repo methods that happen to share the name.
# Typed receivers still resolve these precisely.
_DENY_FALLBACK = frozenset({
    "join", "get", "put", "wait", "close", "open", "read", "write",
    "dump", "dumps", "load", "loads", "run", "start", "stop", "send",
    "append", "extend", "insert", "clear", "copy", "update", "pop",
    "remove", "index", "count", "sort", "items", "keys", "values",
    "result", "add", "set", "flush", "submit", "acquire", "release",
    "mean", "sum",
})

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_WITNESS_CTORS = {
    "named_lock": "Lock",
    "named_rlock": "RLock",
    "named_condition": "Condition",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One concurrency finding, pinned to a file and line."""

    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:  # same shape as tools/lint_jax.py findings
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclasses.dataclass(frozen=True)
class LockDef:
    """One lock creation site with its canonical identity."""

    name: str          # canonical id, e.g. "serve.batcher.DynamicBatcher._cv"
    kind: str          # Lock | RLock | Condition | Semaphore
    path: str
    line: int


@dataclasses.dataclass(frozen=True)
class Edge:
    """One lock-order edge: ``b`` acquired while ``a`` is held."""

    a: str
    b: str
    path: str
    line: int
    chain: str         # human-readable witness, e.g. "_admit -> record_admitted"


@dataclasses.dataclass
class ThreadDef:
    path: str
    line: int
    daemon: bool | None      # None == not specified (defaults non-daemon)
    store: tuple | None      # ("attr", class_name, attr) | ("local", name)
    func_qualname: str
    joined: bool = False


class _FuncInfo:
    """Per-function record: AST node plus the facts the walker extracts."""

    __slots__ = ("module", "qualname", "cls", "node", "path",
                 "acquires", "blocking", "callbacks", "calls",
                 "sum_acquires", "sum_blocking", "sum_callbacks",
                 "acquire_events", "call_events", "return_type")

    def __init__(self, module, qualname, cls, node, path):
        self.module = module
        self.qualname = qualname          # "Class.method" or "func"
        self.cls = cls                    # _ClassInfo | None
        self.node = node
        self.path = path
        # direct facts (filled by the event walker)
        self.acquires: set[str] = set()                 # lock ids acquired here
        self.blocking: list[tuple] = []                 # (kind, line, chain)
        self.callbacks: list[tuple] = []                # (spelled, line, chain)
        self.acquire_events: list[tuple] = []           # (lock, held, line)
        self.call_events: list[tuple] = []              # (callee, held, line, spelled)
        # transitive summaries (fixpoint)
        self.sum_acquires: set[str] = set()
        self.sum_blocking: list[tuple] = []
        self.sum_callbacks: list[tuple] = []
        self.return_type: str | None = None

    @property
    def key(self):
        return (self.module, self.qualname)


class _ClassInfo:
    __slots__ = ("name", "module", "path", "node", "methods", "attr_locks",
                 "attr_types", "attr_threads", "attr_queues", "attr_events")

    def __init__(self, name, module, path, node):
        self.name = name
        self.module = module
        self.path = path
        self.node = node
        self.methods: dict[str, _FuncInfo] = {}
        self.attr_locks: dict[str, str] = {}      # attr -> lock id
        self.attr_types: dict[str, str] = {}      # attr -> class name
        self.attr_threads: set[str] = set()       # attrs holding Thread objects
        self.attr_queues: set[str] = set()        # attrs holding queue.Queue
        self.attr_events: set[str] = set()        # attrs holding threading.Event


class _Module:
    __slots__ = ("name", "path", "tree", "source_lines", "imports",
                 "classes", "functions", "module_locks", "module_types",
                 "module_queues", "module_events")

    def __init__(self, name, path, tree, source_lines):
        self.name = name
        self.path = path
        self.tree = tree
        self.source_lines = source_lines
        self.imports: dict[str, str] = {}          # local name -> dotted target
        self.classes: dict[str, _ClassInfo] = {}
        self.functions: dict[str, _FuncInfo] = {}  # module-level defs
        self.module_locks: dict[str, str] = {}     # global name -> lock id
        self.module_types: dict[str, str] = {}
        self.module_queues: set[str] = set()
        self.module_events: set[str] = set()


# ---------------------------------------------------------------------------
# small AST helpers

def _dotted(node) -> str | None:
    """`a.b.c` -> "a.b.c" (Names/Attributes only)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ann_class_names(ann) -> list[str]:
    """Class names mentioned in an annotation node (handles string
    annotations, Optional/union spellings)."""
    names: list[str] = []
    if ann is None:
        return names
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return names
    for sub in ast.walk(ann):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.append(sub.attr)
    return names


def _call_ctor(node):
    """If `node` is a Call of a threading lock/queue/thread/event ctor (or
    a lockwitness factory), return ("lock", kind, name_literal|None) /
    ("thread",) / ("queue",) / ("event",). Else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if name in _LOCK_CTORS:
        return ("lock", name, None)
    if name in _WITNESS_CTORS:
        lit = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            lit = node.args[0].value
        return ("lock", _WITNESS_CTORS[name], lit)
    if name == "Thread":
        return ("thread",)
    if name in ("Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"):
        return ("queue",)
    if name == "Event":
        return ("event",)
    return None


def _unwrap_or(node):
    """`a or Ctor(...)` -> the Call; used for `self.stats = stats or
    ServerStats(...)` style defaulting."""
    if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or):
        for v in node.values:
            if isinstance(v, ast.Call):
                return v
    return node


class ConcurrencyAnalyzer:
    """Interprocedural lock-order / thread-lifecycle analysis over a set
    of Python sources.  Build with :func:`analyze_paths`."""

    def __init__(self):
        self.modules: dict[str, _Module] = {}
        self.class_index: dict[str, _ClassInfo] = {}
        self.method_index: dict[str, list[_FuncInfo]] = {}
        self.func_index: dict[tuple, _FuncInfo] = {}
        self.locks: dict[str, LockDef] = {}
        self.threads: list[ThreadDef] = []
        self.edges: list[Edge] = []
        self.findings: list[Finding] = []
        self.suppressed: list[tuple[Finding, str]] = []   # (finding, justification)

    # -- phase 1: parse + inventory -------------------------------------

    def add_source(self, source: str, path: str, module: str) -> None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return
        mod = _Module(module, path, tree, source.splitlines())
        self.modules[module] = mod
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    mod.imports[a.asname or a.name] = f"{node.module}.{a.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = _FuncInfo(module, node.name, None, node, path)
                mod.functions[node.name] = fi
                self.func_index[fi.key] = fi
            elif isinstance(node, ast.ClassDef):
                ci = _ClassInfo(node.name, module, path, node)
                mod.classes[node.name] = ci
                self.class_index.setdefault(node.name, ci)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fi = _FuncInfo(module, f"{node.name}.{sub.name}",
                                       ci, sub, path)
                        ci.methods[sub.name] = fi
                        self.func_index[fi.key] = fi
                        self.method_index.setdefault(sub.name, []).append(fi)
                    elif isinstance(sub, ast.AnnAssign) and \
                            isinstance(sub.target, ast.Name):
                        for cn in _ann_class_names(sub.annotation):
                            ci.attr_types.setdefault(sub.target.id, cn)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._module_assign(mod, node)

    def _module_assign(self, mod: _Module, node) -> None:
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        value = getattr(node, "value", None)
        if value is None:
            return
        ctor = _call_ctor(value)
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if ctor and ctor[0] == "lock":
                lock_id = ctor[2] or f"{mod.name}.{t.id}"
                mod.module_locks[t.id] = lock_id
                self._def_lock(lock_id, ctor[1], mod.path, value.lineno)
            elif ctor and ctor[0] == "queue":
                mod.module_queues.add(t.id)
            elif ctor and ctor[0] == "event":
                mod.module_events.add(t.id)
            elif isinstance(value, ast.Call):
                fn = value.func
                cn = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if cn and cn in self.class_index or (cn and cn[:1].isupper()):
                    mod.module_types[t.id] = cn

    def _def_lock(self, lock_id, kind, path, line) -> None:
        self.locks.setdefault(lock_id, LockDef(lock_id, kind, path, line))

    # -- phase 2: class attribute analysis -------------------------------

    def infer_class_attrs(self) -> None:
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for m in ci.methods.values():
                    ann_params = self._param_annotations(m.node)
                    for stmt in ast.walk(m.node):
                        if isinstance(stmt, ast.Assign):
                            tgts, value = stmt.targets, stmt.value
                        elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                            tgts, value = [stmt.target], stmt.value
                        else:
                            continue
                        for t in tgts:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                self._class_attr(ci, t.attr, value,
                                                 ann_params, stmt.lineno)

    def _param_annotations(self, fn_node) -> dict[str, str]:
        out = {}
        args = fn_node.args
        for a in list(args.args) + list(args.kwonlyargs):
            for cn in _ann_class_names(a.annotation):
                if cn in self.class_index:
                    out[a.arg] = cn
                    break
        return out

    def _class_attr(self, ci: _ClassInfo, attr, value, ann_params, line):
        value = _unwrap_or(value)
        ctor = _call_ctor(value)
        if ctor and ctor[0] == "lock":
            lock_id = ctor[2] or f"{ci.module}.{ci.name}.{attr}"
            ci.attr_locks.setdefault(attr, lock_id)
            self._def_lock(lock_id, ctor[1], ci.path, line)
            return
        if ctor and ctor[0] == "thread":
            ci.attr_threads.add(attr)
            return
        if ctor and ctor[0] == "queue":
            ci.attr_queues.add(attr)
            return
        if ctor and ctor[0] == "event":
            ci.attr_events.add(attr)
            return
        if isinstance(value, ast.Call):
            fn = value.func
            cn = fn.id if isinstance(fn, ast.Name) else None
            if cn and cn in self.class_index:
                ci.attr_types.setdefault(attr, cn)
                return
            # reg.counter(...) style: resolve via unique method name's
            # return annotation
            if isinstance(fn, ast.Attribute):
                cands = self.method_index.get(fn.attr, [])
                if len(cands) == 1 and cands[0].return_type:
                    ci.attr_types.setdefault(attr, cands[0].return_type)
                return
        if isinstance(value, ast.Name) and value.id in ann_params:
            ci.attr_types.setdefault(attr, ann_params[value.id])

    def compute_return_types(self) -> None:
        for fi in self.func_index.values():
            returns = getattr(fi.node, "returns", None)
            for cn in _ann_class_names(returns):
                if cn in self.class_index:
                    fi.return_type = cn
                    break

    # -- phase 3: per-function event walk --------------------------------

    def walk_functions(self) -> None:
        for mod in self.modules.values():
            for fi in mod.functions.values():
                _EventWalker(self, mod, fi).run()
            for ci in mod.classes.values():
                for fi in ci.methods.values():
                    _EventWalker(self, mod, fi).run()

    # -- phase 4: interprocedural summaries (fixpoint) -------------------

    def summarize(self, max_iter: int = 12, max_chain: int = 4) -> None:
        for fi in self.func_index.values():
            fi.sum_acquires = set(fi.acquires)
            fi.sum_blocking = [(k, ln, ch) for k, ln, ch in fi.blocking]
            fi.sum_callbacks = [(s, ln, ch) for s, ln, ch in fi.callbacks]
        for _ in range(max_iter):
            changed = False
            for fi in self.func_index.values():
                for callee, _held, _line, spelled in fi.call_events:
                    if callee is None or callee is fi:
                        continue
                    before = len(fi.sum_acquires)
                    fi.sum_acquires |= callee.sum_acquires
                    if len(fi.sum_acquires) != before:
                        changed = True
                    for k, ln, ch in callee.sum_blocking:
                        chain = f"{spelled} -> {ch}" if ch else spelled
                        if chain.count("->") >= max_chain:
                            continue
                        ent = (k, ln, chain)
                        if ent not in fi.sum_blocking:
                            fi.sum_blocking.append(ent)
                            changed = True
                    for s, ln, ch in callee.sum_callbacks:
                        chain = f"{spelled} -> {ch}" if ch else spelled
                        if chain.count("->") >= max_chain:
                            continue
                        ent = (s, ln, chain)
                        if ent not in fi.sum_callbacks:
                            fi.sum_callbacks.append(ent)
                            changed = True
            if not changed:
                break

    # -- phase 5: findings ------------------------------------------------

    def derive(self) -> None:
        self._derive_edges()
        self._derive_cc101()
        self._derive_cc102_cc105()
        self._derive_cc104()

    def _derive_edges(self) -> None:
        seen: dict[tuple, Edge] = {}
        for fi in self.func_index.values():
            for lock, held, line in fi.acquire_events:
                for h in held:
                    if h == lock:
                        continue
                    key = (h, lock)
                    if key not in seen:
                        e = Edge(h, lock, fi.path, line, fi.qualname)
                        seen[key] = e
            for callee, held, line, spelled in fi.call_events:
                if callee is None or not held:
                    continue
                for b in callee.sum_acquires:
                    for h in held:
                        if h == b:
                            continue
                        key = (h, b)
                        if key not in seen:
                            chain = f"{fi.qualname} -> {spelled}"
                            seen[key] = Edge(h, b, fi.path, line, chain)
        self.edges = list(seen.values())

    def _derive_cc101(self) -> None:
        graph: dict[str, dict[str, Edge]] = {}
        for e in self.edges:
            graph.setdefault(e.a, {})[e.b] = e
        reported: set[frozenset] = set()
        # 2-cycles (the classic ABBA) plus longer cycles via bounded DFS
        for a, outs in graph.items():
            for b, e_ab in outs.items():
                e_ba = graph.get(b, {}).get(a)
                if e_ba is not None:
                    key = frozenset((a, b))
                    if key in reported:
                        continue
                    reported.add(key)
                    self._emit(e_ab.path, e_ab.line, "CC101",
                               f"lock-order cycle between '{a}' and '{b}': "
                               f"{a} -> {b} at {e_ab.path}:{e_ab.line} "
                               f"(via {e_ab.chain}); {b} -> {a} at "
                               f"{e_ba.path}:{e_ba.line} (via {e_ba.chain})")
        # longer cycles: DFS with path, depth-capped
        def dfs(start, node, path, visited):
            for nxt, edge in graph.get(node, {}).items():
                if nxt == start and len(path) > 2:
                    key = frozenset(p[0] for p in path)
                    if key not in reported:
                        reported.add(key)
                        first = path[0][1]
                        loop = " -> ".join([p[0] for p in path] + [start])
                        self._emit(first.path, first.line, "CC101",
                                   f"lock-order cycle: {loop}")
                elif nxt not in visited and len(path) < 6:
                    dfs(start, nxt, path + [(nxt, edge)], visited | {nxt})
        for a in graph:
            dfs(a, a, [(a, next(iter(graph[a].values())))], {a})

    def _derive_cc102_cc105(self) -> None:
        emitted: set[tuple] = set()
        for fi in self.func_index.values():
            # direct blocking ops under a held lock
            for kind, line, chain in fi.blocking:
                held = chain[0] if isinstance(chain, tuple) else None
            for callee, held, line, spelled in fi.call_events:
                if callee is None or not held:
                    continue
                for kind, _bl, ch in callee.sum_blocking:
                    key = (fi.path, line, "CC102", kind)
                    if key in emitted:
                        continue
                    emitted.add(key)
                    via = f"{spelled} -> {ch}" if ch else spelled
                    self._emit(fi.path, line, "CC102",
                               f"{kind} reachable while holding "
                               f"{self._fmt_held(held)} (via {via})")
                for s, _bl, ch in callee.sum_callbacks:
                    key = (fi.path, line, "CC105", s)
                    if key in emitted:
                        continue
                    emitted.add(key)
                    via = f"{spelled} -> {ch}" if ch else spelled
                    self._emit(fi.path, line, "CC105",
                               f"callback '{s}' reachable while holding "
                               f"{self._fmt_held(held)} (via {via})")

    @staticmethod
    def _fmt_held(held) -> str:
        return " + ".join(f"'{h}'" for h in held)

    def _derive_cc104(self) -> None:
        # collect join receivers across the repo
        joined_attrs: set[tuple[str, str]] = set()   # (class, attr)
        joined_locals: set[tuple] = set()            # (func key, name)
        for fi in self.func_index.values():
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "join"):
                    recv = node.func.value
                    if (isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == "self" and fi.cls):
                        joined_attrs.add((fi.cls.name, recv.attr))
                    elif isinstance(recv, ast.Name):
                        joined_locals.add((fi.key, recv.id))
        for td in self.threads:
            if td.daemon is True:
                continue
            if td.store and td.store[0] == "attr":
                if (td.store[1], td.store[2]) in joined_attrs:
                    continue
            elif td.store and td.store[0] == "local":
                if any(name == td.store[1] for _k, name in joined_locals):
                    continue
            self._emit(td.path, td.line, "CC104",
                       "non-daemon Thread with no reachable join() owner "
                       f"(spawned in {td.func_qualname}); pass daemon=True "
                       "or join it on every path")

    # -- suppression ------------------------------------------------------

    def _emit(self, path, line, rule, message) -> None:
        f = Finding(path, line, rule, message)
        mod = self._module_for_path(path)
        text = ""
        if mod and 0 < line <= len(mod.source_lines):
            text = mod.source_lines[line - 1]
        m = _PRAGMA_RE.search(text)
        if m and rule in {r.strip() for r in m.group(1).split(",")}:
            why = (m.group(2) or "").strip()
            if not why:
                self.findings.append(Finding(
                    path, line, "CC100",
                    f"pragma suppressing {rule} has no justification — "
                    "add one after a colon"))
                return
            self.suppressed.append((f, why))
            return
        allow = self._allowlisted(path, rule)
        if allow is not None:
            self.suppressed.append((f, allow))
            return
        self.findings.append(f)

    def _allowlisted(self, path, rule) -> str | None:
        for suffix, rules in DEFAULT_ALLOWLIST.items():
            if path.endswith(suffix) and rule in rules:
                return rules[rule]
        return None

    def _module_for_path(self, path) -> _Module | None:
        for mod in self.modules.values():
            if mod.path == path:
                return mod
        return None

    # -- report -----------------------------------------------------------

    def report(self) -> dict:
        """JSON-safe summary: inventory, edges, findings, suppressions."""
        return {
            "locks": [dataclasses.asdict(ld)
                      for ld in sorted(self.locks.values(),
                                       key=lambda d: d.name)],
            "threads": len(self.threads),
            "edges": [dataclasses.asdict(e)
                      for e in sorted(self.edges, key=lambda e: (e.a, e.b))],
            "findings": [f.as_dict() for f in
                         sorted(self.findings,
                                key=lambda f: (f.path, f.line, f.rule))],
            "suppressed": [{**f.as_dict(), "justification": why,
                            "pragma": "allowed"}
                           for f, why in self.suppressed],
        }

    def static_edges(self) -> list[tuple[str, str]]:
        """The (a, b) lock-order pairs, for the runtime witness
        cross-check (obs/lockwitness.py)."""
        return sorted({(e.a, e.b) for e in self.edges})


class _EventWalker:
    """Walk one function body maintaining the held-lock stack, emitting
    acquire / call / blocking / callback events on the owning
    _FuncInfo.  Nested def/lambda bodies are separate functions and are
    NOT walked as part of this frame."""

    def __init__(self, an: ConcurrencyAnalyzer, mod: _Module, fi: _FuncInfo):
        self.an = an
        self.mod = mod
        self.fi = fi
        self.locals: dict[str, tuple] = {}   # name -> ("lock", id) | ("type", cls)
        #                                      | ("thread",) | ("queue",) | ("event",)
        self._harvest_params()

    def _harvest_params(self):
        ann = self.an._param_annotations(self.fi.node)
        for name, cls in ann.items():
            self.locals[name] = ("type", cls)

    def run(self):
        node = self.fi.node
        self._body(node.body, ())

    # -- statement dispatch ----------------------------------------------

    def _body(self, stmts, held):
        i = 0
        while i < len(stmts):
            stmt = stmts[i]
            consumed = self._stmt(stmt, held, stmts, i)
            i += 1 + consumed

    def _stmt(self, stmt, held, siblings, idx) -> int:
        """Walk one statement; returns extra siblings consumed (for the
        acquire(); try/finally idiom)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return 0  # separate scope — not executed at this point
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                self._expr(item.context_expr, new_held, is_with=True)
                lock = self._resolve_lock(item.context_expr)
                if lock:
                    self.fi.acquires.add(lock)
                    self.fi.acquire_events.append(
                        (lock, new_held, item.context_expr.lineno))
                    new_held = new_held + (lock,)
            self._body(stmt.body, new_held)
            return 0
        if isinstance(stmt, ast.Try):
            self._body(stmt.body, held)
            for h in stmt.handlers:
                self._body(h.body, held)
            self._body(stmt.orelse, held)
            self._body(stmt.finalbody, held)
            return 0
        if isinstance(stmt, ast.If):
            # `if X.acquire(blocking=False):` guarded try/finally idiom
            lock = self._acquire_call_lock(stmt.test)
            if lock is not None:
                self.fi.acquires.add(lock)
                self.fi.acquire_events.append((lock, held, stmt.test.lineno))
                if not self._guarded_release(stmt.body, lock):
                    self.an._emit(self.fi.path, stmt.test.lineno, "CC103",
                                  f"manual acquire of '{lock}' not followed "
                                  "by try/finally release")
                self._body(stmt.body, held + (lock,))
                self._body(stmt.orelse, held)
                return 0
            self._expr(stmt.test, held)
            self._body(stmt.body, held)
            self._body(stmt.orelse, held)
            return 0
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._harvest_loop_target(stmt)
            self._body(stmt.body, held)
            self._body(stmt.orelse, held)
            return 0
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, held)
            self._body(stmt.body, held)
            self._body(stmt.orelse, held)
            return 0
        if isinstance(stmt, ast.Expr):
            lock = self._acquire_call_lock(stmt.value)
            if lock is not None:
                self.fi.acquires.add(lock)
                self.fi.acquire_events.append((lock, held, stmt.lineno))
                nxt = siblings[idx + 1] if idx + 1 < len(siblings) else None
                if isinstance(nxt, ast.Try) and \
                        self._releases_in_finally(nxt, lock):
                    self._body(nxt.body, held + (lock,))
                    for h in nxt.handlers:
                        self._body(h.body, held + (lock,))
                    self._body(nxt.orelse, held + (lock,))
                    self._body(nxt.finalbody, held)
                    return 1
                self.an._emit(self.fi.path, stmt.lineno, "CC103",
                              f"manual acquire of '{lock}' not followed "
                              "by try/finally release")
                # conservatively treat as held for the rest of the block
                return 0
            self._expr(stmt.value, held)
            return 0
        if isinstance(stmt, ast.Assign):
            self._assign(stmt, held)
            return 0
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign_one(stmt.target, stmt.value, held)
            return 0
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._expr(stmt.value, held)
            return 0
        if isinstance(stmt, (ast.Raise, ast.Assert, ast.AugAssign,
                             ast.Delete)):
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.expr):
                    self._expr(sub, held)
            return 0
        return 0

    def _harvest_loop_target(self, stmt):
        # `for lane in self._lanes:` — element types are unknown; leave
        # the target unresolved (unique-method-name fallback still
        # resolves `lane.join()` etc. when the method name is unique).
        pass

    def _assign(self, stmt, held):
        for t in stmt.targets:
            self._assign_one(t, stmt.value, held)

    def _assign_one(self, target, value, held):
        self._expr(value, held)
        uv = _unwrap_or(value)
        ctor = _call_ctor(uv)
        binding = None
        if ctor and ctor[0] == "lock":
            lock_id = ctor[2] or self._local_lock_id(target)
            self.an._def_lock(lock_id, ctor[1], self.fi.path, value.lineno)
            binding = ("lock", lock_id)
        elif ctor and ctor[0] == "thread":
            binding = ("thread",)
            self._record_thread(uv, target)
        elif ctor and ctor[0] == "queue":
            binding = ("queue",)
        elif ctor and ctor[0] == "event":
            binding = ("event",)
        else:
            binding = self._value_binding(uv)
        if binding and isinstance(target, ast.Name):
            self.locals[target.id] = binding

    def _local_lock_id(self, target):
        name = target.id if isinstance(target, ast.Name) else (
            target.attr if isinstance(target, ast.Attribute) else "anon")
        return f"{self.mod.name}.{self.fi.qualname}.{name}"

    def _value_binding(self, value):
        """Resolve the RHS of an assignment to a known binding."""
        if isinstance(value, ast.Attribute):
            lock = self._resolve_lock(value)
            if lock:
                return ("lock", lock)
            t = self._attr_type(value)
            if t:
                return ("type", t)
            return None
        if isinstance(value, ast.Name):
            return self.locals.get(value.id)
        if isinstance(value, ast.Call):
            # x.__dict__.setdefault("_plan_lock", threading.Lock()) and
            # _LOCKS.setdefault(key, threading.Lock()) idioms
            fn = value.func
            if isinstance(fn, ast.Attribute) and fn.attr == "setdefault" \
                    and len(value.args) == 2:
                inner = _call_ctor(value.args[1])
                if inner and inner[0] == "lock":
                    key = value.args[0]
                    if isinstance(key, ast.Constant) and \
                            isinstance(key.value, str):
                        lock_id = f"{self.mod.name}.{key.value}"
                    else:
                        recv = _dotted(fn.value) or "locks"
                        lock_id = f"{self.mod.name}.{recv.split('.')[0]}"
                    self.an._def_lock(lock_id, inner[1], self.fi.path,
                                      value.lineno)
                    return ("lock", lock_id)
            cls = self._call_return_type(value)
            if cls:
                return ("type", cls)
        return None

    def _record_thread(self, call, target):
        # a stored spawn (`t = Thread(...)`) is seen twice: once by the
        # expression walk (as an inline spawn) and once by the
        # assignment handler (with its binding) — keep only the record
        # that carries the join-tracking binding
        inline = isinstance(target, ast.Name) and target.id == "_inline_"
        for i, td in enumerate(self.an.threads):
            if td.path == self.fi.path and td.line == call.lineno:
                if inline:
                    return
                del self.an.threads[i]
                break
        daemon = None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        store = None
        if isinstance(target, ast.Name):
            store = ("local", target.id)
        elif (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and self.fi.cls):
            store = ("attr", self.fi.cls.name, target.attr)
        self.an.threads.append(ThreadDef(
            self.fi.path, call.lineno, daemon, store, self.fi.qualname))

    # -- expression walk ---------------------------------------------------

    def _expr(self, node, held, is_with=False):
        for call in self._calls_in(node):
            self._classify_call(call, held, top_is_with=is_with and call is node)

    def _calls_in(self, node):
        out = []
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue  # deferred scope
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return out

    def _classify_call(self, call, held, top_is_with=False):
        fn = call.func
        dotted = _dotted(fn)
        # thread spawned inline: Thread(...).start()
        ctor = _call_ctor(call)
        if ctor and ctor[0] == "thread":
            self._record_thread(call, ast.Name(id="_inline_"))
            return
        # blocking module functions (resolve through import aliases)
        if dotted:
            root = dotted.split(".")[0]
            full = self.mod.imports.get(root)
            spelled = dotted
            if full:
                resolved = full + dotted[len(root):]
            else:
                resolved = dotted
            for (m, f), kind in _BLOCKING_FUNCS.items():
                if resolved == f"{m}.{f}" or spelled == f"{m}.{f}":
                    self._blocking(kind, call, held)
                    return
        if isinstance(fn, ast.Attribute):
            meth = fn.attr
            if meth in _BLOCKING_ANY_METHOD:
                self._blocking(_BLOCKING_ANY_METHOD[meth], call, held)
                return
            if meth == "join" and self._receiver_is_thread(fn.value):
                self._blocking("thread join", call, held)
                return
            if meth in ("get", "put") and self._receiver_is_queue(fn.value):
                self._blocking(f"queue {meth}", call, held)
                return
            if meth == "wait":
                recv_lock = self._resolve_lock(fn.value)
                if recv_lock and recv_lock in held:
                    return  # Condition.wait on held cv releases it — safe
                if self._receiver_is_event(fn.value) or recv_lock:
                    self._blocking("wait on event/condition", call, held)
                    return
            if meth == "result" and not isinstance(fn.value, ast.Constant):
                self._blocking("future result()", call, held)
                return
            if meth in ("acquire", "release"):
                return  # handled at statement level
        # callback call: direct `self.on_x(...)` / `cb(...)` alias
        spelled_cb = self._callback_spelling(fn)
        if spelled_cb:
            self.fi.callbacks.append((spelled_cb, call.lineno, ""))
            if held:
                self.an._emit(self.fi.path, call.lineno, "CC105",
                              f"callback '{spelled_cb}' invoked while "
                              f"holding {ConcurrencyAnalyzer._fmt_held(held)}")
            return
        # plain call: resolve for the interprocedural graph
        callee = self._resolve_callee(fn)
        spelled = dotted or "<call>"
        self.fi.call_events.append((callee, held, call.lineno, spelled))

    def _blocking(self, kind, call, held):
        self.fi.blocking.append((kind, call.lineno, ""))
        if held:
            self.an._emit(self.fi.path, call.lineno, "CC102",
                          f"{kind} while holding "
                          f"{ConcurrencyAnalyzer._fmt_held(held)}")

    def _callback_spelling(self, fn):
        if isinstance(fn, ast.Attribute) and _CALLBACK_NAME_RE.search(fn.attr):
            # skip known repo functions with hook-ish names (they are
            # analyzed interprocedurally instead)
            if self._resolve_callee(fn) is None:
                return _dotted(fn) or fn.attr
        if isinstance(fn, ast.Name) and _CALLBACK_NAME_RE.search(fn.id):
            if self.locals.get(fn.id, (None,))[0] is None \
                    and self._resolve_callee(fn) is None:
                return fn.id
        return None

    # -- resolution helpers ------------------------------------------------

    def _resolve_lock(self, node) -> str | None:
        """Resolve an expression to a lock identity, or None."""
        if isinstance(node, ast.Name):
            b = self.locals.get(node.id)
            if b and b[0] == "lock":
                return b[1]
            if node.id in self.mod.module_locks:
                return self.mod.module_locks[node.id]
            return None
        if isinstance(node, ast.Attribute):
            base = node.value
            attr = node.attr
            if isinstance(base, ast.Name) and base.id == "self" and self.fi.cls:
                lock = self.fi.cls.attr_locks.get(attr)
                if lock:
                    return lock
            # typed receiver
            t = self._receiver_type(base)
            if t:
                ci = self.an.class_index.get(t)
                if ci and attr in ci.attr_locks:
                    return ci.attr_locks[attr]
            # module alias: obs_runtime._lock
            if isinstance(base, ast.Name):
                target = self.mod.imports.get(base.id)
                if target:
                    m = self._module_by_dotted(target)
                    if m and attr in m.module_locks:
                        return m.module_locks[attr]
            # unique attr name repo-wide
            cands = {ci.attr_locks[attr]
                     for ci in self.an.class_index.values()
                     if attr in ci.attr_locks}
            if len(cands) == 1:
                return next(iter(cands))
            return None
        return None

    def _module_by_dotted(self, dotted):
        # "mmlspark_tpu.obs.runtime" -> module "obs.runtime"
        name = dotted
        for prefix in ("mmlspark_tpu.",):
            if name.startswith(prefix):
                name = name[len(prefix):]
        return self.an.modules.get(name)

    def _attr_type(self, node) -> str | None:
        if not isinstance(node, ast.Attribute):
            return None
        base, attr = node.value, node.attr
        if self._is_external(base):
            return None
        if isinstance(base, ast.Name) and base.id == "self" and self.fi.cls:
            return self.fi.cls.attr_types.get(attr)
        if isinstance(base, ast.Name):
            target = self.mod.imports.get(base.id)
            if target:
                m = self._module_by_dotted(target)
                if m:
                    return m.module_types.get(attr)
        t = self._receiver_type(base)
        if t:
            ci = self.an.class_index.get(t)
            if ci:
                return ci.attr_types.get(attr)
        # unique attr-name type repo-wide
        cands = {ci.attr_types[attr] for ci in self.an.class_index.values()
                 if attr in ci.attr_types}
        if len(cands) == 1:
            return next(iter(cands))
        return None

    def _receiver_type(self, node) -> str | None:
        if isinstance(node, ast.Name):
            if node.id == "self" and self.fi.cls:
                return self.fi.cls.name
            b = self.locals.get(node.id)
            if b and b[0] == "type":
                return b[1]
            if node.id in self.mod.module_types:
                return self.mod.module_types[node.id]
            return None
        if isinstance(node, ast.Attribute):
            return self._attr_type(node)
        if isinstance(node, ast.Call):
            return self._call_return_type(node)
        return None

    def _call_return_type(self, call) -> str | None:
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.an.class_index:
                return fn.id
            target = self.mod.imports.get(fn.id)
            if target and target.rsplit(".", 1)[-1] in self.an.class_index:
                return target.rsplit(".", 1)[-1]
            fi = self._resolve_callee(fn)
            return fi.return_type if fi else None
        if isinstance(fn, ast.Attribute):
            fi = self._resolve_callee(fn)
            return fi.return_type if fi else None
        return None

    def _receiver_is_thread(self, node) -> bool:
        if isinstance(node, ast.Name):
            b = self.locals.get(node.id)
            return bool(b and b[0] == "thread")
        if isinstance(node, ast.Attribute):
            base, attr = node.value, node.attr
            if isinstance(base, ast.Name) and base.id == "self" and self.fi.cls:
                if attr in self.fi.cls.attr_threads:
                    return True
            t = self._receiver_type(base)
            if t:
                ci = self.an.class_index.get(t)
                if ci and attr in ci.attr_threads:
                    return True
            # unique thread-attr name repo-wide
            owners = [ci for ci in self.an.class_index.values()
                      if attr in ci.attr_threads]
            nonthread = any(attr in ci.attr_types or attr in ci.attr_locks
                            for ci in self.an.class_index.values())
            return bool(owners) and not nonthread
        return False

    def _receiver_is_queue(self, node) -> bool:
        if isinstance(node, ast.Name):
            b = self.locals.get(node.id)
            if b and b[0] == "queue":
                return True
            return node.id in self.mod.module_queues
        if isinstance(node, ast.Attribute):
            base, attr = node.value, node.attr
            if isinstance(base, ast.Name) and base.id == "self" and self.fi.cls:
                return attr in self.fi.cls.attr_queues
            t = self._receiver_type(base)
            if t:
                ci = self.an.class_index.get(t)
                return bool(ci and attr in ci.attr_queues)
        return False

    def _receiver_is_event(self, node) -> bool:
        if isinstance(node, ast.Name):
            b = self.locals.get(node.id)
            if b and b[0] == "event":
                return True
            return node.id in self.mod.module_events
        if isinstance(node, ast.Attribute):
            base, attr = node.value, node.attr
            if isinstance(base, ast.Name) and base.id == "self" and self.fi.cls:
                if attr in self.fi.cls.attr_events:
                    return True
            t = self._receiver_type(base)
            if t:
                ci = self.an.class_index.get(t)
                if ci and attr in ci.attr_events:
                    return True
            owners = [ci for ci in self.an.class_index.values()
                      if attr in ci.attr_events]
            others = any(attr in ci.attr_types or attr in ci.attr_locks
                         or attr in ci.attr_threads or attr in ci.attr_queues
                         for ci in self.an.class_index.values())
            return bool(owners) and not others
        return False

    def _acquire_call_lock(self, node) -> str | None:
        """If `node` is `<lock>.acquire(...)`, return the lock id."""
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"):
            return self._resolve_lock(node.func.value)
        return None

    def _guarded_release(self, body, lock) -> bool:
        """True if `body` (the if-acquire suite) is a try/finally that
        releases `lock` (leading comments/logs before the try allowed)."""
        for stmt in body:
            if isinstance(stmt, ast.Try) and \
                    self._releases_in_finally(stmt, lock):
                return True
        return False

    def _releases_in_finally(self, try_stmt, lock) -> bool:
        for stmt in try_stmt.finalbody:
            for call in ast.walk(stmt):
                if (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == "release"
                        and self._resolve_lock(call.func.value) == lock):
                    return True
        return False

    @staticmethod
    def _chain_root(node):
        while isinstance(node, ast.Attribute):
            node = node.value
        return node if isinstance(node, ast.Name) else None

    def _is_external(self, node) -> bool:
        """True when the receiver chain is rooted at an import of a
        module we are NOT analyzing (os, json, time, numpy...) — never
        guess a repo callee for those."""
        root = self._chain_root(node)
        if root is None or root.id == "self":
            return False
        if root.id in self.locals or root.id in self.mod.module_types:
            return False
        target = self.mod.imports.get(root.id)
        return target is not None and self._module_by_dotted(target) is None

    def _resolve_callee(self, fn) -> _FuncInfo | None:
        if isinstance(fn, ast.Name):
            fi = self.mod.functions.get(fn.id)
            if fi:
                return fi
            target = self.mod.imports.get(fn.id)
            if target and "." in target:
                mod_dotted, name = target.rsplit(".", 1)
                m = self._module_by_dotted(mod_dotted)
                if m:
                    return m.functions.get(name)
            return None
        if isinstance(fn, ast.Attribute):
            meth = fn.attr
            base = fn.value
            if self._is_external(base):
                return None
            # module alias call: _rt.record(...)
            if isinstance(base, ast.Name):
                target = self.mod.imports.get(base.id)
                if target:
                    m = self._module_by_dotted(target)
                    if m:
                        return m.functions.get(meth)
            if isinstance(base, ast.Name) and base.id == "self" and self.fi.cls:
                if meth in self.fi.cls.methods:
                    return self.fi.cls.methods[meth]
                # inherited methods: search bases by name
                for b in self.fi.cls.node.bases:
                    bn = b.id if isinstance(b, ast.Name) else None
                    bc = self.an.class_index.get(bn) if bn else None
                    if bc and meth in bc.methods:
                        return bc.methods[meth]
                return None
            t = self._receiver_type(base)
            if t:
                ci = self.an.class_index.get(t)
                if ci and meth in ci.methods:
                    return ci.methods[meth]
            if meth in _DENY_FALLBACK:
                return None
            cands = self.an.method_index.get(meth, [])
            if len(cands) == 1:
                return cands[0]
            return None
        return None


# ---------------------------------------------------------------------------
# entry points

def _module_name(path: str, root: str) -> str:
    rel = os.path.relpath(path, root)
    name = rel[:-3].replace(os.sep, ".")
    for prefix in ("mmlspark_tpu.",):
        if name.startswith(prefix):
            name = name[len(prefix):]
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def analyze_sources(sources: Iterable[tuple[str, str, str]],
                    ) -> ConcurrencyAnalyzer:
    """Run the full pass over (source, path, module) triples."""
    an = ConcurrencyAnalyzer()
    for source, path, module in sources:
        an.add_source(source, path, module)
    an.compute_return_types()
    an.infer_class_attrs()
    an.walk_functions()
    an.summarize()
    an.derive()
    return an


def analyze_paths(paths: Iterable[str], root: str | None = None,
                  ) -> ConcurrencyAnalyzer:
    """Analyze .py files (or directory trees) as one program."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    if root is None:
        root = os.path.commonpath([os.path.dirname(os.path.abspath(f))
                                   for f in files]) if files else "."
        # anchor at the package parent when analyzing the package itself
        for f in files:
            parts = os.path.abspath(f).split(os.sep)
            if "mmlspark_tpu" in parts:
                root = os.sep.join(
                    parts[: parts.index("mmlspark_tpu")]) or os.sep
                break

    def gen():
        for f in files:
            try:
                with open(f, encoding="utf-8") as fh:
                    src = fh.read()
            except OSError:
                continue
            yield src, f, _module_name(os.path.abspath(f), root)

    return analyze_sources(gen())


def analyze_repo(repo_root: str | None = None) -> ConcurrencyAnalyzer:
    """Analyze the mmlspark_tpu package itself (the tier-1 gate entry)."""
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    pkg = os.path.join(repo_root, "mmlspark_tpu")
    return analyze_paths([pkg], root=repo_root)

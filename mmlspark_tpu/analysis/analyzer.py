"""The pre-flight pipeline analyzer — transformSchema-style validation.

``analyze`` abstractly interprets a ``Pipeline``/``PipelineModel`` (or a
bare stage list) over a :class:`~mmlspark_tpu.analysis.info.TableSchema`:
each stage's ``infer_schema`` hook maps the incoming abstract schema to
its output schema, contract violations surface as stage-indexed
:class:`Diagnostic`\\ s instead of deep-in-XLA shape errors, and the
device-plan audit replays the pipeline planner's segmentation symbolically
(fusion boundaries, predicted H2D/D2H crossings against the
one-per-minibatch contract, recompile hazards). No ``DataTable`` is built
and no device transfer or compilation happens — the only tracing is
``jax.eval_shape`` inside model stages' own hooks, and the only jax
runtime touch is device *enumeration* (``jax.local_devices``) for the
audit's dp arithmetic; pre-flight callers on shared accelerator hosts
should pin ``JAX_PLATFORMS=cpu`` (the CLI does).

The reference's analog is SparkML ``transformSchema`` chained through
``Pipeline.fit`` (reference: core/schema SparkSchema/SchemaConstants);
here the walk additionally predicts the device plan PR 1's executor would
choose, because on TPU the expensive mistake is not a late type error but
an unplanned host round-trip or recompile.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from mmlspark_tpu.analysis.audit import (
    PlanAudit, PlanSegmentReport, standalone_crossings,
)
from mmlspark_tpu.analysis.info import (
    KIND_IMAGE, KIND_UNKNOWN, KIND_VECTOR, ColumnInfo, SchemaError,
    TableSchema,
)

_SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Diagnostic:
    """One typed finding, anchored to the stage that caused it."""

    severity: str            # "error" | "warning" | "info"
    code: str                # stable kebab-case identifier
    message: str
    stage_index: int | None = None
    stage: str = ""          # stage type name

    def __str__(self) -> str:
        where = (f" stage {self.stage_index} ({self.stage})"
                 if self.stage_index is not None else "")
        return f"[{self.severity}]{where}: {self.message} ({self.code})"


@dataclasses.dataclass
class AnalysisReport:
    """Everything ``analyze`` proves about a pipeline."""

    diagnostics: list
    schema: TableSchema          # predicted output schema
    plan: PlanAudit | None = None

    @property
    def errors(self) -> list:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> list:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def format(self) -> str:
        lines = []
        order = {s: k for k, s in enumerate(_SEVERITIES)}
        for d in sorted(self.diagnostics,
                        key=lambda d: (order.get(d.severity, 9),
                                       d.stage_index or 0)):
            lines.append(str(d))
        if not self.diagnostics:
            lines.append("no findings: pipeline is well-formed")
        lines.append("")
        lines.append("predicted output schema:")
        for name, info in self.schema.columns.items():
            shape = "" if info.shape is None else f" {list(info.shape)}"
            lines.append(f"  {name}: {info.kind}"
                         f"{'' if info.dtype is None else ' ' + info.dtype}"
                         f"{shape}")
        if self.plan is not None:
            lines.append("")
            lines.append("device plan:")
            lines.extend("  " + ln for ln in self.plan.format().splitlines())
        return "\n".join(lines)


def _stages_of(pipeline: Any) -> list:
    """Accept a Pipeline, PipelineModel, stage list, or single stage."""
    from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
    if isinstance(pipeline, (Pipeline, PipelineModel)):
        return list(pipeline.stages or [])
    if isinstance(pipeline, (list, tuple)):
        return list(pipeline)
    return [pipeline]


def check_stage_kinds(stages: Any) -> list:
    """Diagnostics for entries that are not pipeline stages at all — the
    pre-validation ``Pipeline.fit`` runs so a mis-wired list fails with the
    offending index/type instead of an opaque error mid-fit."""
    from mmlspark_tpu.core.stage import Estimator, Transformer
    out = []
    for i, s in enumerate(_stages_of(stages)):
        if not isinstance(s, (Transformer, Estimator)):
            out.append(Diagnostic(
                "error", "not-a-pipeline-stage",
                f"stage {i} ({type(s).__name__}) is neither Transformer "
                f"nor Estimator — every pipeline stage must be one; "
                f"got {s!r:.120}", i, type(s).__name__))
    return out


def _drain_pending(schema: TableSchema, diags: list, idx: int,
                   name: str) -> None:
    for severity, code, message in schema.pending:
        diags.append(Diagnostic(severity, code, message, idx, name))
    schema.pending = []


def _advance(stage: Any, idx: int, schema: TableSchema, rows: int | None,
             diags: list) -> tuple[TableSchema, int | None]:
    """Apply one stage's schema inference, degrading gracefully on errors."""
    name = type(stage).__name__
    new_rows = rows
    try:
        new_schema, new_rows = stage._infer_state(schema, rows)
        _drain_pending(new_schema, diags, idx, name)
    except SchemaError as e:
        diags.append(Diagnostic("error", e.code, e.message, idx, name))
        # recover: outputs exist but nothing is known about them, so one
        # mis-wired stage yields one diagnostic, not a cascade
        new_schema = schema.copy()
        for col in getattr(stage, "_declared_output_columns", list)() or []:
            new_schema.columns[col] = ColumnInfo.unknown()
    except Exception as e:  # a buggy hook must not kill the analysis
        diags.append(Diagnostic(
            "warning", "schema-inference-failed",
            f"infer_schema raised {type(e).__name__}: {e}", idx, name))
        new_schema = schema.as_inexact()
        new_rows = None
    # shadowing: overwriting a column with a *different* kind is the classic
    # image-vs-vector confusion source — flag it at the write site
    for col, info in new_schema.columns.items():
        old = schema.get(col)
        if (old is not None and old.kind != KIND_UNKNOWN
                and info.kind != KIND_UNKNOWN and old.kind != info.kind):
            diags.append(Diagnostic(
                "warning", "column-shadowed",
                f"column {col!r} ({old.kind}) overwritten as {info.kind}; "
                "stages downstream that expect the original layout will "
                "misread it", idx, name))
    return new_schema, new_rows


def _purpose_collisions(schema: TableSchema) -> list:
    """Two columns stamped with the same (purpose, model_uid) — evaluators
    resolving by purpose would pick one arbitrarily."""
    from mmlspark_tpu.core.schema import SchemaConstants
    seen: dict[tuple, list[str]] = {}
    for col, info in schema.columns.items():
        purpose = info.meta.get(SchemaConstants.K_COLUMN_PURPOSE)
        if purpose is None:
            continue
        uid = info.meta.get(SchemaConstants.K_MODEL_UID)
        seen.setdefault((purpose, uid), []).append(col)
    out = []
    for (purpose, uid), cols in seen.items():
        if len(cols) > 1:
            out.append(Diagnostic(
                "warning", "score-purpose-collision",
                f"columns {cols} all claim purpose {purpose!r} for model "
                f"{uid!r}; find_score_column will return {cols[0]!r} "
                "arbitrarily"))
    return out


def analyze(pipeline: Any, schema: TableSchema, n_rows: int | None = None,
            device_audit: bool = True,
            precision: Any = None) -> AnalysisReport:
    """Statically validate a pipeline over an abstract input schema.

    ``n_rows``, when given, turns the device-plan audit's crossing
    prediction concrete (minibatch counts); without it the audit still
    reports segmentation and hazards. Set ``device_audit=False`` to skip
    the plan replay (pure schema checking). ``precision`` resolves each
    device segment's serving :class:`~mmlspark_tpu.core.precision.
    PrecisionPolicy` in the report (mode + expected parity tolerance —
    what ``tools/analyze.py pipeline --precision`` prints); the emitted
    column dtypes are policy-independent (the composite restores the
    declared ``ArrayMeta`` dtypes), so schema predictions don't change.
    """
    from mmlspark_tpu.core import plan
    from mmlspark_tpu.core.precision import PrecisionPolicy
    from mmlspark_tpu.core.stage import DeviceStage

    policy = PrecisionPolicy.parse(precision)
    stages = _stages_of(pipeline)
    diags = list(check_stage_kinds(stages))
    bad = {d.stage_index for d in diags}
    schema = schema.copy()
    audit = PlanAudit() if device_audit else None
    uploads = 0
    crossings_exact = True
    rows = n_rows

    i = 0
    while i < len(stages):
        stage = stages[i]
        if i in bad:
            i += 1
            continue
        seg = None
        explain: list = []
        if device_audit and rows != 0:
            try:
                # a precision query is about the SERVING plan, which
                # dispatches even a lone device stage through the fused
                # path (transform_async min_stages=1) — the offline view
                # keeps the planner's >= 2 rule
                seg = plan.collect_segment(
                    stages, i, schema.entry_meta, explain=explain,
                    min_stages=(1 if policy is not None
                                and policy.active else 2),
                    precision=policy)
            except Exception as e:
                diags.append(Diagnostic(
                    "warning", "plan-audit-failed",
                    f"device-plan replay raised {type(e).__name__}: {e}",
                    i, type(stage).__name__))
        if seg is not None:
            m = None
            if rows is not None:
                try:
                    m = plan.predict_segment_minibatches(seg, rows)
                except Exception:
                    m = None
            if m is None:
                crossings_exact = False
            else:
                uploads += m
            audit.segments.append(PlanSegmentReport(
                "device", seg.start, seg.end,
                [type(s).__name__ for s in seg.stages],
                entry_col=seg.entry_col, minibatches=m,
                out_dtypes={c: meta.dtype
                            for c, meta in seg.out_metas.items()},
                precision=(policy.mode if policy is not None
                           and policy.active else "f32"),
                tolerance=(policy.resolve_tolerance()
                           if policy is not None and policy.active
                           else 0.0)))
            for j in range(seg.start, seg.end):
                schema, rows = _advance(stages[j], j, schema, rows, diags)
            i = seg.end
            continue

        # host step
        if device_audit:
            if isinstance(stage, DeviceStage) and rows != 0:
                in_col = stage.device_input_col()
                info = schema.get(in_col) if in_col else None
                if (info is not None
                        and info.kind in (KIND_IMAGE, KIND_VECTOR)
                        and info.concrete_shape is None
                        and not info.has_missing):
                    diags.append(Diagnostic(
                        "warning", "shape-polymorphic-entry",
                        f"column {in_col!r} feeds device-capable stage "
                        f"{type(stage).__name__} with a per-row shape that "
                        "is not statically fixed: each distinct shape "
                        "compiles a fresh program (recompile hazard) or "
                        "falls back to host", i, type(stage).__name__))
            m = None
            try:
                m = standalone_crossings(stage, schema, rows)
            except Exception:
                m = None
            if m is None:
                crossings_exact = False
            else:
                uploads += m
            audit.segments.append(PlanSegmentReport(
                "host", i, i + 1, [type(stage).__name__],
                minibatches=m, notes=list(explain)))
        schema, rows = _advance(stage, i, schema, rows, diags)
        if audit is not None and audit.segments \
                and audit.segments[-1].kind == "host":
            # per-stage output dtypes, from the advanced schema: the
            # declared outputs' predicted dtype (None stays absent)
            declared = getattr(stage, "_declared_output_columns",
                               list)() or []
            audit.segments[-1].out_dtypes = {
                c: schema.columns[c].dtype for c in declared
                if c in schema.columns
                and schema.columns[c].dtype is not None}
        i += 1

    diags.extend(_purpose_collisions(schema))
    if audit is not None:
        audit.uploads = uploads if crossings_exact else None
        audit.fetches = audit.uploads
    return AnalysisReport(diagnostics=diags, schema=schema, plan=audit)

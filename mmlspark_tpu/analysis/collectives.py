"""Collective-schedule extraction — the static model of what a compiled
program will do on the wire.

Every cross-chip interaction in this codebase is a jax collective
(``psum``/``all_gather``/``ppermute``/``all_to_all``/``psum_scatter``)
issued inside a ``shard_map`` body; on a multi-host mesh every process
compiles and runs the SAME program, so the one way to deadlock is for
the *schedule* — the ordered sequence of collectives — to diverge across
processes. That can only happen through data-dependent control flow
(a collective under ``lax.cond``/``lax.while_loop``, whose predicate can
differ per host) or through host-side exchanges racing device dispatch
(the ``drain_barrier`` fence discipline of ``train/input.py``). Both are
statically visible, so this module checks them before anything runs:

* :func:`extract_schedule` walks a jaxpr (recursing through ``pjit``,
  ``scan``, ``while``, ``cond``, ``shard_map`` and custom-derivative
  wrappers) and returns the ordered :class:`CollectiveSchedule`. Each op
  records its mesh axes, its structural context (e.g. a ``ppermute``
  inside the pipeline's scan), the static trip count when one exists,
  and whether it sits under data-dependent control flow.
* :func:`check_schedule` reports deadlocks-in-waiting: collectives under
  data-dependent conditionals (SPMD201) and axis names the mesh does not
  carry (SPMD101).
* :func:`compare_schedules` pins cross-host agreement: two traces of the
  step program (or the same program on two hosts) must produce identical
  fingerprints.
* :func:`check_fence_discipline` is the host-side half: an AST check
  that cross-process exchanges (``process_allgather``,
  ``sync_global_devices``) inside a dispatch loop are preceded by a
  drain fence, so the liveness exchange can never race the in-flight
  step window (docs/training_input.md, "lockstep rules").

The schedule is *predictive*: each jaxpr collective lowers to exactly
one StableHLO collective op (``psum`` → ``all_reduce``, ``ppermute`` →
``collective_permute``, ``psum_scatter`` → ``reduce_scatter``; loops
keep their body ops, so counts are invariant to trip count).
``tests/test_spmd.py`` holds predicted counts equal to the lowered text
of every parallel entry point on the 8-device CPU mesh.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Any, Callable, Iterable

# jaxpr primitive name → schedule kind (the public jax.lax spelling)
COLLECTIVE_PRIMS: dict[str, str] = {
    "psum": "psum",
    "pmax": "pmax",
    "pmin": "pmin",
    "ppermute": "ppermute",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "reduce_scatter": "psum_scatter",   # jax.lax.psum_scatter's primitive
}

# schedule kind → the StableHLO op it lowers to (the observable side of
# the prediction; reductions share all_reduce)
STABLEHLO_OP: dict[str, str] = {
    "psum": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "ppermute": "collective_permute",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "psum_scatter": "reduce_scatter",
}

# sub-jaxpr-carrying primitives that are structurally transparent (no
# control-flow semantics of their own)
_TRANSPARENT = ("pjit", "closed_call", "core_call", "custom_jvp_call",
                "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                "checkpoint", "custom_lin")


def _axes_of(eqn: Any) -> tuple[str, ...]:
    """Mesh axis names a collective eqn operates over."""
    params = eqn.params
    axes = params.get("axes", params.get("axis_name"))
    if axes is None:
        return ()
    if isinstance(axes, str):
        return (axes,)
    return tuple(str(a) for a in axes)


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in program order."""

    kind: str                       # psum | all_gather | ppermute | ...
    axes: tuple[str, ...]           # mesh axes it communicates over
    context: tuple[str, ...]        # structural path, e.g. (shard_map, scan)
    conditional: bool = False       # under data-dependent control flow
    trips: int | None = None        # static trip count (innermost scan)

    def describe(self) -> str:
        where = "/".join(self.context) or "top"
        s = f"{self.kind}({','.join(self.axes)}) @ {where}"
        if self.trips is not None:
            s += f" ×{self.trips}"
        if self.conditional:
            s += " [data-dependent!]"
        return s


@dataclasses.dataclass
class CollectiveSchedule:
    """The ordered collective sequence of one traced program."""

    ops: list[CollectiveOp] = dataclasses.field(default_factory=list)

    def counts(self) -> dict[str, int]:
        """Static op counts by kind — one per program site, matching how
        each site appears exactly once in the lowered StableHLO text
        (loop bodies lower once, whatever the trip count)."""
        out: dict[str, int] = {}
        for op in self.ops:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    def stablehlo_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.ops:
            hlo = STABLEHLO_OP[op.kind]
            out[hlo] = out.get(hlo, 0) + 1
        return out

    def axes_used(self) -> set[str]:
        return {a for op in self.ops for a in op.axes}

    def fingerprint(self) -> tuple:
        """Order-sensitive identity for cross-host agreement checks."""
        return tuple((op.kind, op.axes, op.context, op.conditional,
                      op.trips) for op in self.ops)

    def conditional_ops(self) -> list[CollectiveOp]:
        return [op for op in self.ops if op.conditional]

    def format(self) -> str:
        if not self.ops:
            return "(no collectives)"
        return "\n".join(f"  {i}. {op.describe()}"
                         for i, op in enumerate(self.ops))


def _sub_jaxpr(obj: Any) -> Any:
    """Unwrap ClosedJaxpr → Jaxpr."""
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


def _walk(jaxpr: Any, context: tuple[str, ...], conditional: bool,
          trips: int | None, out: list[CollectiveOp]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            axes = _axes_of(eqn)
            if not axes:
                continue  # psum over no axes: an identity the grad
                # transpose machinery emits; nothing crosses the wire
                # (and nothing appears in the lowered program)
            out.append(CollectiveOp(COLLECTIVE_PRIMS[name], axes,
                                    context, conditional, trips))
        elif name == "shard_map":
            _walk(_sub_jaxpr(eqn.params["jaxpr"]),
                  context + ("shard_map",), conditional, trips, out)
        elif name == "scan":
            _walk(_sub_jaxpr(eqn.params["jaxpr"]), context + ("scan",),
                  conditional, int(eqn.params.get("length") or 0) or None,
                  out)
        elif name == "while":
            # trip count is data-dependent: any collective inside is a
            # cross-host divergence hazard
            _walk(_sub_jaxpr(eqn.params["cond_jaxpr"]),
                  context + ("while.cond",), True, None, out)
            _walk(_sub_jaxpr(eqn.params["body_jaxpr"]),
                  context + ("while.body",), True, None, out)
        elif name == "cond":
            for b, branch in enumerate(eqn.params["branches"]):
                _walk(_sub_jaxpr(branch), context + (f"cond.branch{b}",),
                      True, trips, out)
        elif name in _TRANSPARENT:
            sub = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                   or eqn.params.get("fun_jaxpr"))
            if sub is not None:
                _walk(_sub_jaxpr(sub), context, conditional, trips, out)


def extract_schedule(traced: Any, *example_args: Any) -> CollectiveSchedule:
    """Collective schedule of ``traced`` — a ClosedJaxpr/Jaxpr, or a
    callable traced with ``jax.make_jaxpr`` over ``example_args`` (shape
    structs are fine; nothing executes)."""
    if callable(traced) and not hasattr(traced, "eqns") \
            and not hasattr(traced, "jaxpr"):
        import jax
        traced = jax.make_jaxpr(traced)(*example_args)
    ops: list[CollectiveOp] = []
    _walk(_sub_jaxpr(traced), (), False, None, ops)
    return CollectiveSchedule(ops)


def lowered_collective_counts(text: str) -> dict[str, int]:
    """Count StableHLO collective ops in ``jax.jit(f).lower(...).as_text()``
    — the observed side of the schedule prediction. Matches both the
    pretty (``stablehlo.all_reduce(...)``) and generic
    (``"stablehlo.all_reduce"(...)``) MLIR spellings."""
    import re

    out: dict[str, int] = {}
    for op in set(STABLEHLO_OP.values()):
        n = len(re.findall(rf'stablehlo\.{op}"?[ (]', text))
        if n:
            out[op] = n
    return out


# ---- checks ----


@dataclasses.dataclass(frozen=True)
class SpmdFinding:
    """One verifier finding; codes are catalogued in
    docs/spmd_analysis.md (SPMD1xx sharding, SPMD2xx schedule)."""

    code: str
    where: str
    message: str

    def __str__(self) -> str:
        return f"{self.where}: {self.code} {self.message}"


def check_schedule(schedule: CollectiveSchedule,
                   mesh_axes: Iterable[str]) -> list[SpmdFinding]:
    """Schedule-level hazards: data-dependent collectives and unknown
    axis names."""
    known = set(mesh_axes)
    findings: list[SpmdFinding] = []
    for op in schedule.ops:
        bad = [a for a in op.axes if a not in known]
        if bad:
            findings.append(SpmdFinding(
                "SPMD101", "/".join(op.context) or "top",
                f"collective {op.kind} names axes {bad} the mesh does not "
                f"carry (mesh axes: {sorted(known)})"))
        if op.conditional:
            findings.append(SpmdFinding(
                "SPMD201", "/".join(op.context),
                f"collective {op.kind}({','.join(op.axes)}) under "
                "data-dependent control flow: hosts whose predicate "
                "differs will disagree on the collective schedule — a "
                "deadlock-in-waiting. Hoist the collective out of the "
                "cond/while (compute both sides, select after)"))
    return findings


def compare_schedules(a: CollectiveSchedule, b: CollectiveSchedule,
                      where: str = "schedule") -> list[SpmdFinding]:
    """Cross-host agreement: two traces of the same logical program must
    produce the identical ordered schedule."""
    fa, fb = a.fingerprint(), b.fingerprint()
    if fa == fb:
        return []
    n = min(len(fa), len(fb))
    for i in range(n):
        if fa[i] != fb[i]:
            return [SpmdFinding(
                "SPMD202", where,
                f"collective schedules diverge at position {i}: "
                f"{a.ops[i].describe()} vs {b.ops[i].describe()} — "
                "processes running these programs will deadlock")]
    return [SpmdFinding(
        "SPMD202", where,
        f"collective schedules diverge in length: {len(fa)} vs {len(fb)} "
        "ops — processes running these programs will deadlock")]


# ---- host-side fence discipline (AST) ----

_EXCHANGE_CALLS = {"process_allgather", "sync_global_devices"}
_FENCE_CALLS = {"drain_barrier", "fence"}


def _callee(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def check_fence_discipline(source: str,
                           path: str = "<string>") -> list[SpmdFinding]:
    """Cross-process host exchanges inside a loop must follow a drain
    fence (``drain_barrier()``/``fence()``) *earlier in the same loop
    body*: an allgather issued while device steps are still in flight
    interleaves differently per process, deadlocking the step
    collectives (the PR 3 lockstep rule, now statically checked)."""
    findings: list[SpmdFinding] = []
    tree = ast.parse(source, filename=path)
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        fence_lines = [n.lineno for n in ast.walk(loop)
                       if isinstance(n, ast.Call)
                       and _callee(n.func) in _FENCE_CALLS]
        for node in ast.walk(loop):
            if isinstance(node, ast.Call) \
                    and _callee(node.func) in _EXCHANGE_CALLS:
                if not any(ln <= node.lineno for ln in fence_lines):
                    findings.append(SpmdFinding(
                        "SPMD203", f"{path}:{node.lineno}",
                        f"{_callee(node.func)} inside a loop with no "
                        "preceding drain fence: the exchange can race "
                        "in-flight step dispatch and deadlock the step "
                        "collectives — call drain_barrier() first"))
    return findings

"""Pre-flight static analysis of pipelines — prove a pipeline well-formed
and predict its device plan before any data moves.

* :func:`analyze` — transformSchema-style abstract interpretation of a
  Pipeline/PipelineModel over a :class:`TableSchema`, with typed
  stage-indexed diagnostics and a device-plan audit (fusion boundaries,
  predicted H2D/D2H crossings, recompile hazards).
* :class:`TableSchema` / :class:`ColumnInfo` — the abstract table values.
* :mod:`~mmlspark_tpu.analysis.spmd` /
  :mod:`~mmlspark_tpu.analysis.collectives` — the symbolic SPMD verifier
  for the parallel layer and multi-chip plans: sharding-state
  propagation through shard_map contracts, partial-sum escape and
  capacity/divisibility hazards, collective-schedule extraction with
  cross-host agreement and fence checks (docs/spmd_analysis.md).
* :mod:`~mmlspark_tpu.analysis.concurrency` — the **whole-repo
  concurrency verifier**: pure-AST interprocedural lock/thread
  inventory, lock-order graph, and typed findings (CC101 lock-order
  cycle, CC102 blocking under lock, CC103 unguarded acquire, CC104
  joinless thread, CC105 callback under lock), paired with the runtime
  lock-order witness in :mod:`~mmlspark_tpu.obs.lockwitness`
  (docs/concurrency.md).
* ``tools/analyze.py`` is the CLI entry point; ``tools/lint_jax.py`` is
  the companion AST lint for JAX anti-patterns in the codebase itself.
"""

from mmlspark_tpu.analysis.analyzer import (  # noqa: F401
    AnalysisReport, Diagnostic, analyze, check_stage_kinds,
)
from mmlspark_tpu.analysis.audit import (  # noqa: F401
    PlanAudit, PlanSegmentReport, TrainPreprocessAudit,
    audit_train_preprocess,
)
from mmlspark_tpu.analysis.collectives import (  # noqa: F401
    CollectiveOp, CollectiveSchedule, SpmdFinding, compare_schedules,
    extract_schedule,
)
from mmlspark_tpu.analysis.concurrency import (  # noqa: F401
    ConcurrencyAnalyzer, analyze_paths, analyze_repo, analyze_sources,
)
from mmlspark_tpu.analysis.fingerprint import (  # noqa: F401
    plan_fingerprints,
)
from mmlspark_tpu.analysis.info import (  # noqa: F401
    ColumnInfo, SchemaError, TableSchema,
)
from mmlspark_tpu.analysis.spmd import (  # noqa: F401
    PlanSpmdAudit, ShardState, SpmdReport, audit_plan_spmd, verify_function,
    verify_parallel_layer, verify_repo,
)

__all__ = [
    "AnalysisReport",
    "CollectiveOp",
    "CollectiveSchedule",
    "ColumnInfo",
    "ConcurrencyAnalyzer",
    "Diagnostic",
    "PlanAudit",
    "PlanSegmentReport",
    "PlanSpmdAudit",
    "SchemaError",
    "ShardState",
    "SpmdFinding",
    "SpmdReport",
    "TableSchema",
    "TrainPreprocessAudit",
    "analyze",
    "analyze_paths",
    "analyze_repo",
    "analyze_sources",
    "audit_plan_spmd",
    "audit_train_preprocess",
    "check_stage_kinds",
    "compare_schedules",
    "extract_schedule",
    "plan_fingerprints",
    "verify_function",
    "verify_parallel_layer",
    "verify_repo",
]

"""Pre-flight static analysis of pipelines — prove a pipeline well-formed
and predict its device plan before any data moves.

* :func:`analyze` — transformSchema-style abstract interpretation of a
  Pipeline/PipelineModel over a :class:`TableSchema`, with typed
  stage-indexed diagnostics and a device-plan audit (fusion boundaries,
  predicted H2D/D2H crossings, recompile hazards).
* :class:`TableSchema` / :class:`ColumnInfo` — the abstract table values.
* ``tools/analyze.py`` is the CLI entry point; ``tools/lint_jax.py`` is
  the companion AST lint for JAX anti-patterns in the codebase itself.
"""

from mmlspark_tpu.analysis.analyzer import (  # noqa: F401
    AnalysisReport, Diagnostic, analyze, check_stage_kinds,
)
from mmlspark_tpu.analysis.audit import (  # noqa: F401
    PlanAudit, PlanSegmentReport,
)
from mmlspark_tpu.analysis.info import (  # noqa: F401
    ColumnInfo, SchemaError, TableSchema,
)

__all__ = [
    "AnalysisReport",
    "ColumnInfo",
    "Diagnostic",
    "PlanAudit",
    "PlanSegmentReport",
    "SchemaError",
    "TableSchema",
    "analyze",
    "check_stage_kinds",
]

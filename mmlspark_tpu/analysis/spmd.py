"""Symbolic SPMD verifier — static sharding propagation and collective
checking for the parallel layer and multi-chip plans.

PR 2's analyzer proves a *pipeline* well-formed before data moves; this
module does the same for the *parallel* execution paths, where the
failure modes are silent numerics corruption and cross-host deadlock
rather than a schema error. Every parallel module here runs inside
``shard_map`` with the replication check off (``check_vma=False`` — the
per-shard code needs ``axis_index``), which means jax no longer verifies
the replication claims ``out_specs`` make. The verifier re-checks them
statically:

* **Sharding-state lattice** (:class:`ShardState`): each array dim is
  replicated or sharded over a tuple of mesh axes, and a value as a
  whole may additionally be *varying* (an unreduced partial state) over
  axes — the three-level lattice ``replicated ⊑ sharded ⊑ partial``.
  :func:`varying_axes` runs a VMA-style dataflow over a shard_map body
  jaxpr: inputs vary over the axes their ``in_specs`` shard,
  ``axis_index`` introduces variance, ``psum``/``all_gather`` over an
  axis removes it, ``psum_scatter``/``all_to_all`` introduce it, and
  everything else unions. An output claimed replicated over an axis it
  still varies over is an **unreduced partial sum escaping** (SPMD103)
  — exactly the class of bug ``check_vma=False`` stops jax from seeing.
* **Call-site provenance** (SPMD103/SPMD102): a shard_map operand built
  by trace-time structure ops (``jnp.stack``/``concatenate`` — the
  re-stacked pipeline layer params) without an explicit replication pin
  hits the jax ≤ 0.4.37 GSPMD full-to-shard sharp edge: mesh axes the
  ``in_spec`` leaves unmentioned consume the operand as an unreduced
  partial sum (dp-extent × the true value — the dp×pp loss-parity seed
  bug). The verifier requires such operands to pass through
  ``with_sharding_constraint``/``device_put`` pinned replicated over the
  unmentioned axes (:func:`~mmlspark_tpu.parallel.pipeline.commit_replicated`).
* **Divisibility / capacity hazards** (SPMD104): dims that do not divide
  by their sharding axes' extents, and — for capacity-dispatch contracts
  (MoE) — dispatch collectives issued with no cross-shard count exchange
  first, the pad-capacity bug class: slot budgets split per source shard
  make a token's survival depend on where its padding landed.
* **Collective schedules** (:mod:`~mmlspark_tpu.analysis.collectives`):
  ordered psum/all_gather/ppermute/all_to_all/psum_scatter extraction
  with conditional-collective (SPMD201), cross-host agreement (SPMD202)
  and drain-fence (SPMD203) checks.

Entry points: :func:`verify_function` for any traceable callable,
:data:`ENTRY_POINTS`/:func:`verify_parallel_layer` for the declared
contracts of ``parallel/{moe,pipeline,ring_attention}``, and
:func:`audit_plan_spmd` — the device-plan audit's multi-chip mode: a
fused inference segment must contain **zero** manual collectives (XLA
inserts the dp resharding; a hand-rolled collective in an inference
composite is a bug) and its minibatch sizing must divide the mesh's
data extent. ``tools/analyze.py spmd`` is the CLI; the repo-wide gate
(:func:`verify_repo`) runs in tier-1 via ``tools/perf_smoke.py``.

Verification work registers through the one telemetry substrate
(``mmlspark_tpu/obs``): ``analysis.spmd.*`` counters and a
``spmd/verify`` span per verified function.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Callable, Iterable

from mmlspark_tpu.analysis.collectives import (
    COLLECTIVE_PRIMS, CollectiveSchedule, SpmdFinding, check_fence_discipline,
    check_schedule, compare_schedules, extract_schedule,
)
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import span as _obs_span

# ---- the sharding-state lattice ----


@dataclasses.dataclass(frozen=True)
class ShardState:
    """Abstract placement of one array on a mesh.

    ``dims[i]`` is the tuple of mesh axes dim ``i`` is sharded over
    (empty = replicated along that dim); ``partial`` is the set of axes
    over which the VALUE is an unreduced partial state (each shard holds
    a different contribution that has not been reduced). The lattice:
    ``replicated ⊑ sharded(dims) ⊑ partial(axes)`` — a partial value
    must meet a reducing collective before it may escape as replicated.
    """

    dims: tuple[tuple[str, ...], ...]
    partial: frozenset = frozenset()

    @classmethod
    def from_names(cls, names: dict, ndim: int) -> "ShardState":
        """From a shard_map ``in_names``/``out_names`` dim→axes dict."""
        return cls(tuple(tuple(names.get(d, ())) for d in range(ndim)))

    def axes_used(self) -> set[str]:
        return {a for axes in self.dims for a in axes} | set(self.partial)

    @property
    def is_replicated(self) -> bool:
        return not self.axes_used()

    def describe(self) -> str:
        spec = ", ".join("×".join(axes) if axes else "·"
                         for axes in self.dims)
        s = f"[{spec}]"
        if self.partial:
            s += f" partial({','.join(sorted(self.partial))})"
        return s


def check_divisibility(state: ShardState, shape: tuple[int, ...],
                       mesh_shape: dict, where: str) -> list[SpmdFinding]:
    """SPMD104: a sharded dim must divide by its axes' total extent, or
    the per-shard padding silently skews whatever is computed from it."""
    findings = []
    for d, axes in enumerate(state.dims):
        ext = math.prod(mesh_shape.get(a, 1) for a in axes)
        if ext > 1 and shape[d] % ext:
            findings.append(SpmdFinding(
                "SPMD104", where,
                f"dim {d} of size {shape[d]} does not divide by the "
                f"{'×'.join(axes)} extent {ext}: implicit per-shard "
                "padding — make the padding (and who owns the pad rows) "
                "explicit"))
    return findings


# ---- varying-axes dataflow over a shard_map body ----

_REMOVES_VARIANCE = {"psum", "pmax", "pmin", "all_gather"}
_ADDS_VARIANCE = {"reduce_scatter", "all_to_all"}


def _eqn_axes(eqn: Any) -> set[str]:
    params = eqn.params
    axes = params.get("axes", params.get("axis_name"))
    if axes is None:
        return set()
    if isinstance(axes, str):
        return {axes}
    return {str(a) for a in axes}


def _propagate(jaxpr: Any, in_sets: list) -> list:
    """Map invar varying-axes sets to outvar sets through one jaxpr."""
    env: dict[Any, frozenset] = {}

    def read(v: Any) -> frozenset:
        if not hasattr(v, "count"):  # Literal
            return frozenset()
        return env.get(v, frozenset())

    def write(v: Any, s: frozenset) -> None:
        if hasattr(v, "count"):
            env[v] = s

    for v, s in zip(jaxpr.invars, in_sets):
        write(v, frozenset(s))
    for v in jaxpr.constvars:
        write(v, frozenset())

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        joined = frozenset().union(*[read(v) for v in eqn.invars]) \
            if eqn.invars else frozenset()
        if name == "axis_index":
            out = joined | _eqn_axes(eqn)
        elif name in _REMOVES_VARIANCE:
            out = joined - _eqn_axes(eqn)
        elif name in _ADDS_VARIANCE:
            out = joined | _eqn_axes(eqn)
        elif name == "ppermute":
            out = joined  # permuting identical values stays identical
        elif name == "scan":
            outs = _fixpoint_scan(eqn, [read(v) for v in eqn.invars])
            for v, s in zip(eqn.outvars, outs):
                write(v, s)
            continue
        elif name == "while":
            outs = _fixpoint_while(eqn, [read(v) for v in eqn.invars])
            for v, s in zip(eqn.outvars, outs):
                write(v, s)
            continue
        elif name == "cond":
            pred = read(eqn.invars[0])
            ops = [read(v) for v in eqn.invars[1:]]
            branch_outs = None
            for br in eqn.params["branches"]:
                bo = _propagate(br.jaxpr if hasattr(br, "jaxpr") else br,
                                ops)
                branch_outs = bo if branch_outs is None else [
                    a | b for a, b in zip(branch_outs, bo)]
            for v, s in zip(eqn.outvars, branch_outs or []):
                write(v, s | pred)
            continue
        elif "jaxpr" in eqn.params or "call_jaxpr" in eqn.params:
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            if len(sub.invars) == len(eqn.invars):
                outs = _propagate(sub, [read(v) for v in eqn.invars])
                for v, s in zip(eqn.outvars, outs):
                    write(v, s)
                continue
            out = joined
        else:
            out = joined
        for v in eqn.outvars:
            write(v, out)
    return [read(v) for v in jaxpr.outvars]


def _fixpoint_scan(eqn: Any, in_sets: list) -> list:
    sub = eqn.params["jaxpr"]
    sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
    nc, ncarry = eqn.params["num_consts"], eqn.params["num_carry"]
    consts, carry, xs = in_sets[:nc], in_sets[nc:nc + ncarry], \
        in_sets[nc + ncarry:]
    for _ in range(8):  # axes sets only grow; tiny fixpoint
        outs = _propagate(sub, consts + carry + xs)
        new_carry = [a | b for a, b in zip(carry, outs[:ncarry])]
        if new_carry == carry:
            break
        carry = new_carry
    outs = _propagate(sub, consts + carry + xs)
    return [a | b for a, b in zip(carry, outs[:ncarry])] + outs[ncarry:]


def _fixpoint_while(eqn: Any, in_sets: list) -> list:
    body = eqn.params["body_jaxpr"]
    body = body.jaxpr if hasattr(body, "jaxpr") else body
    cn, bn = eqn.params["cond_nconsts"], eqn.params["body_nconsts"]
    bconsts = in_sets[cn:cn + bn]
    carry = in_sets[cn + bn:]
    for _ in range(8):
        outs = _propagate(body, bconsts + carry)
        new_carry = [a | b for a, b in zip(carry, outs)]
        if new_carry == carry:
            break
        carry = new_carry
    return carry


def varying_axes(body_jaxpr: Any, in_states: list[ShardState]) -> list:
    """Axes each body output may still vary over, given input states:
    an input varies over every axis its spec shards (each shard holds a
    different slice) plus its declared partial axes."""
    in_sets = [frozenset(st.axes_used()) for st in in_states]
    return _propagate(body_jaxpr, in_sets)


# ---- shard_map call-site verification ----

# producer primitives that pin an operand's sharding before shard_map
# entry (the legal way to feed a trace-computed value in)
_PIN_PRIMS = {"sharding_constraint", "device_put"}
# trace-time structure builders — the stack_layer_params class that hits
# the GSPMD full-to-shard partial-sum edge when fed in unpinned
_STRUCTURE_PRIMS = {"concatenate"}
# value-preserving views walked through when resolving provenance
_VIEW_PRIMS = {"reshape", "squeeze", "expand_dims", "transpose",
               "convert_element_type", "broadcast_in_dim", "rev"}


def _pin_replicates(eqn: Any, axes: set[str]) -> bool:
    """Does this sharding_constraint/device_put pin leave ``axes``
    unsharded (replicated)? Unparseable shardings fail safe (False)."""
    sh = eqn.params.get("sharding") or eqn.params.get("device")
    spec = getattr(sh, "spec", None)
    if spec is None:
        # device_put carries a list in some versions
        devices = eqn.params.get("devices")
        if devices:
            spec = getattr(devices[0], "spec", None)
    if spec is None:
        return False
    used: set[str] = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, str):
            used.add(entry)
        else:
            used.update(entry)
    return not (used & axes)


def _operand_provenance(var: Any, producers: dict, unmentioned: set[str],
                        depth: int = 12) -> str:
    """'boundary' (jit invar/const — committed), 'pinned' (explicit
    replication constraint), 'structure' (trace-built stack/concat —
    the hazard), or 'traced' (other in-trace computation)."""
    seen = 0
    while seen < depth:
        eqn = producers.get(var)
        if eqn is None:
            return "boundary"
        name = eqn.primitive.name
        if name in _PIN_PRIMS:
            return "pinned" if _pin_replicates(eqn, unmentioned) \
                else "mis-pinned"
        if name in _STRUCTURE_PRIMS:
            return "structure"
        if name in _VIEW_PRIMS and eqn.invars:
            var = eqn.invars[0]
            seen += 1
            continue
        return "traced"
    return "traced"


@dataclasses.dataclass
class ShardMapSite:
    """One verified shard_map call: declared contract + body analysis."""

    where: str
    mesh_shape: dict
    in_states: list[ShardState]
    out_states: list[ShardState]
    schedule: CollectiveSchedule
    findings: list[SpmdFinding]

    def describe(self) -> str:
        ins = ", ".join(s.describe() for s in self.in_states)
        outs = ", ".join(s.describe() for s in self.out_states)
        return f"{self.where}: in ({ins}) → out ({outs})"


def _verify_shard_map_eqn(eqn: Any, producers: dict,
                          where: str) -> ShardMapSite:
    mesh = eqn.params["mesh"]
    mesh_shape = dict(mesh.shape)
    big_axes = {a for a, n in mesh_shape.items() if n > 1}
    body = eqn.params["jaxpr"]
    body = body.jaxpr if hasattr(body, "jaxpr") else body
    findings: list[SpmdFinding] = []

    in_states = []
    for k, (names, var) in enumerate(zip(eqn.params["in_names"],
                                         eqn.invars)):
        ndim = len(getattr(var.aval, "shape", ()))
        st = ShardState.from_names(names, ndim)
        in_states.append(st)
        # SPMD101: axis names the mesh does not carry
        bad = [a for a in st.axes_used() if a not in mesh_shape]
        if bad:
            findings.append(SpmdFinding(
                "SPMD101", where,
                f"operand {k} in_spec names axes {bad} the mesh does not "
                f"carry (mesh axes: {sorted(mesh_shape)})"))
        # SPMD104: divisibility of sharded dims
        shape = tuple(getattr(var.aval, "shape", ()))
        findings.extend(check_divisibility(
            st, shape, mesh_shape, f"{where} operand {k}"))
        # SPMD103 (call-site): trace-built operands with unmentioned
        # axes hit the full-to-shard partial-sum edge unless pinned
        unmentioned = big_axes - st.axes_used()
        if unmentioned:
            prov = _operand_provenance(var, producers, unmentioned)
            if prov == "structure":
                findings.append(SpmdFinding(
                    "SPMD103", where,
                    f"operand {k} is built by trace-time stack/concat "
                    f"and enters with mesh axes {sorted(unmentioned)} "
                    "unmentioned in its in_spec: the full-to-shard "
                    "conversion consumes it as an UNREDUCED PARTIAL SUM "
                    "(axis-extent × the true value) under "
                    "check_vma=False. Pin it replicated first "
                    "(parallel.pipeline.commit_replicated)"))
            elif prov == "mis-pinned":
                findings.append(SpmdFinding(
                    "SPMD102", where,
                    f"operand {k} is pinned to a sharding that shards "
                    f"axes {sorted(unmentioned)} its in_spec replicates: "
                    "entry forces an implicit reshard (hidden "
                    "all-gather) — align the pin with the in_spec or "
                    "replicate"))

    # body dataflow: outputs must not vary over axes their out_spec
    # claims replicated (SPMD103 — the check check_vma=False disables)
    out_vary = varying_axes(body, in_states)
    out_states = []
    for k, (names, var, vary) in enumerate(zip(eqn.params["out_names"],
                                               eqn.outvars, out_vary)):
        ndim = len(getattr(var.aval, "shape", ()))
        st = ShardState.from_names(names, ndim)
        claimed_replicated = big_axes - st.axes_used()
        escape = set(vary) & claimed_replicated
        if escape:
            st = dataclasses.replace(st, partial=frozenset(escape))
            findings.append(SpmdFinding(
                "SPMD103", where,
                f"output {k} still varies over {sorted(escape)} but its "
                "out_spec claims replication there: an unreduced "
                "partial-sum value escapes the shard_map — reduce it "
                "(psum/all_gather) before returning"))
        out_states.append(st)

    schedule = extract_schedule(body)
    findings.extend(check_schedule(schedule, mesh_shape))
    return ShardMapSite(where, mesh_shape, in_states, out_states,
                        schedule, findings)


def _shard_map_sites(jaxpr: Any, prefix: str):
    """Yield ``(shard_map eqn, producer map, where)`` at every nesting
    level — a jitted train step wraps its shard_maps in a pjit (and the
    pipeline's in a scan), so site discovery must recurse. The producer
    map is per-level: operands that are that level's invars count as
    boundary values."""
    producers = {v: e for e in jaxpr.eqns for v in e.outvars}
    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        if name == "shard_map":
            yield eqn, producers, f"{prefix}:shard_map[{i}]"
            continue
        subs = []
        if name == "cond":
            subs = [(f"cond[{b}]", br)
                    for b, br in enumerate(eqn.params["branches"])]
        elif name == "while":
            subs = [("while.cond", eqn.params["cond_jaxpr"]),
                    ("while.body", eqn.params["body_jaxpr"])]
        else:
            sub = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                   or eqn.params.get("fun_jaxpr")) \
                if isinstance(eqn.params, dict) else None
            if sub is not None:
                subs = [(name if name not in ("pjit", "closed_call")
                         else "", sub)]
        for label, sub in subs:
            sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            inner_prefix = f"{prefix}/{label}" if label else prefix
            yield from _shard_map_sites(sub, inner_prefix)


# ---- whole-function verification ----


@dataclasses.dataclass
class SpmdReport:
    """Verification result for one traced function."""

    name: str
    schedule: CollectiveSchedule
    sites: list[ShardMapSite]
    findings: list[SpmdFinding]

    @property
    def ok(self) -> bool:
        return not self.findings

    def format(self) -> str:
        lines = [f"spmd: {self.name} — {len(self.sites)} shard_map "
                 f"site(s), {len(self.schedule.ops)} collective(s)"]
        for site in self.sites:
            lines.append(f"  {site.describe()}")
        lines.append("schedule:")
        lines.append(self.schedule.format())
        if self.findings:
            lines.append(f"{len(self.findings)} finding(s):")
            lines.extend(f"  {f}" for f in self.findings)
        else:
            lines.append("no findings")
        return "\n".join(lines)


def _capacity_findings(schedule: CollectiveSchedule,
                       where: str) -> list[SpmdFinding]:
    """SPMD104 (capacity contract): a dispatch collective must be
    preceded by a cross-shard count exchange over the same axis, or the
    slot budget is split per source shard — a token's survival then
    depends on where the batch (and its padding) landed, not on the
    expert's global load (the MoE pad-capacity bug class)."""
    seen_exchange: set[str] = set()
    for op in schedule.ops:
        if op.kind in ("all_gather", "psum"):
            seen_exchange.update(op.axes)
        elif op.kind in ("psum_scatter", "all_to_all"):
            missing = [a for a in op.axes if a not in seen_exchange]
            if missing:
                return [SpmdFinding(
                    "SPMD104", where,
                    f"capacity dispatch ({op.kind} over {missing}) with "
                    "no preceding cross-shard count exchange "
                    "(all_gather/psum of the routed counts): capacity "
                    "slots are assigned per source shard, so padded/"
                    "masked tokens shift which REAL tokens survive — "
                    "assign slot positions globally")]
            return []
    return []


def verify_function(fn: Callable, *args: Any, name: str = "<fn>",
                    capacity_dispatch: bool = False,
                    expect_axes: Iterable[str] | None = None,
                    expect_no_collectives: bool = False) -> SpmdReport:
    """Trace ``fn`` over ``args`` (ShapeDtypeStructs are fine — nothing
    executes) and statically verify every shard_map site, the collective
    schedule, and the declared contract."""
    import jax

    with _obs_span("spmd/verify", "analysis", {"fn": name}):
        closed = jax.make_jaxpr(fn)(*args)
        sites: list[ShardMapSite] = []
        findings: list[SpmdFinding] = []
        for eqn, producers, where in _shard_map_sites(closed.jaxpr, name):
            site = _verify_shard_map_eqn(eqn, producers, where)
            sites.append(site)
            findings.extend(site.findings)
        schedule = extract_schedule(closed)
        if capacity_dispatch:
            findings.extend(_capacity_findings(schedule, name))
        if expect_axes is not None:
            extra = schedule.axes_used() - set(expect_axes)
            if extra:
                findings.append(SpmdFinding(
                    "SPMD101", name,
                    f"communicates over axes {sorted(extra)} outside its "
                    f"declared contract {sorted(set(expect_axes))}"))
        if expect_no_collectives and schedule.ops:
            findings.append(SpmdFinding(
                "SPMD105", name,
                f"{len(schedule.ops)} manual collective(s) in a program "
                "declared collective-free (fused inference segments rely "
                "on XLA-inserted resharding only): "
                f"{[op.describe() for op in schedule.ops]}"))
    if _obs_rt._enabled:
        reg = _obs_registry()
        reg.counter("analysis.spmd.functions_verified").add()
        reg.counter("analysis.spmd.findings").add(len(findings))
    return SpmdReport(name, schedule, sites, findings)


# ---- declared contracts for the parallel layer ----


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """A parallel module's declared sharding contract: the mesh it
    expects, the axes it may communicate over, whether it performs
    capacity dispatch (enabling the count-exchange rule), and whether it
    must be manual-collective-free (the serve dp-replica / GSPMD-tp
    segment contract — XLA-inserted resharding only)."""

    name: str
    mesh_spec: dict
    expect_axes: tuple[str, ...]
    build: Callable                  # (mesh) -> (fn, example_args)
    capacity_dispatch: bool = False
    expect_no_collectives: bool = False


def _build_moe(mesh):
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.parallel.moe import moe_apply
    E, D, DH, N = 8, 16, 32, 64
    params = {
        "gate": jax.ShapeDtypeStruct((D, E), jnp.float32),
        "w_in": jax.ShapeDtypeStruct((E, D, DH), jnp.float32),
        "b_in": jax.ShapeDtypeStruct((E, DH), jnp.float32),
        "w_out": jax.ShapeDtypeStruct((E, DH, D), jnp.float32),
        "b_out": jax.ShapeDtypeStruct((E, D), jnp.float32),
    }
    x = jax.ShapeDtypeStruct((N, D), jnp.float32)
    m = jax.ShapeDtypeStruct((N,), jnp.float32)

    def fn(p, xs, mask):
        return moe_apply(p, xs, mesh, capacity_factor=2.0, token_mask=mask)

    return fn, (params, x, m)


def _build_pipeline(mesh):
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.parallel.pipeline import (
        pipeline_apply, stack_layer_params,
    )
    L, D = 8, 16
    layers = [{"w": jax.ShapeDtypeStruct((D, D), jnp.float32),
               "b": jax.ShapeDtypeStruct((D,), jnp.float32)}
              for _ in range(L)]
    x = jax.ShapeDtypeStruct((16, D), jnp.float32)

    def block_fn(layer, h):
        return h + jnp.tanh(h @ layer["w"] + layer["b"])

    def fn(per_layer, xs):
        # stacked at trace time — the Trainer's calling convention, so
        # the verifier sees the commit_replicated pin (or its absence)
        return pipeline_apply(block_fn, stack_layer_params(per_layer),
                              xs, mesh, num_microbatches=4)

    return fn, (layers, x)


def _build_ring(mesh):
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.parallel.ring_attention import ring_attention
    q = jax.ShapeDtypeStruct((4, 16, 4, 8), jnp.float32)

    def fn(qq, kk, vv):
        return ring_attention(qq, kk, vv, mesh, causal=True)

    return fn, (q, q, q)


def _build_ulysses(mesh):
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.parallel.ring_attention import ulysses_attention
    q = jax.ShapeDtypeStruct((4, 16, 4, 8), jnp.float32)

    def fn(qq, kk, vv):
        return ulysses_attention(qq, kk, vv, mesh)

    return fn, (q, q, q)


def _build_serve_segment(mesh):
    """The sharded serve dispatch entry: the composite
    ``core.plan.dispatch_segment`` jits for a lone-JaxModel segment on
    ``mesh`` — a DP replica's sub-mesh or a GSPMD-tp model-parallel
    layout. The contract either way: ZERO manual collectives (replicas
    are independent; tp resharding is XLA-inserted from the param
    shardings, never hand-rolled)."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core import plan
    from mmlspark_tpu.core.stage import ArrayMeta
    from mmlspark_tpu.models.bundle import ModelBundle
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import MLP

    d_in, width, n_out = 16, 32, 8
    module = MLP(features=(width,), num_outputs=n_out)
    params = jax.eval_shape(module.init, jax.random.PRNGKey(0),
                            jnp.zeros((1, d_in), jnp.float32))["params"]
    bundle = ModelBundle(module=module, params=params, input_spec=(d_in,),
                         output_names=("features", "logits"))
    jm = JaxModel(model=bundle, input_col="x", output_col="scores")
    seg = plan.collect_segment([jm], 0,
                               lambda c: ArrayMeta((d_in,), "float32"),
                               min_stages=1, mesh=mesh)
    composite, params_tuple = plan_segment_composite(seg)
    rows = plan.dp_rounded_minibatch(8, plan.mesh_dp(mesh), 8)
    entry = jax.ShapeDtypeStruct((rows, d_in), jnp.float32)
    return composite, (params_tuple, entry)


def _build_serve_pp(mesh):
    """The pp-sharded serve segment: what a pipelined stage's
    ``device_fn`` wraps — L stacked blocks through
    :func:`~mmlspark_tpu.parallel.pipeline.pipeline_apply` under the
    bucket ladder. Manual collectives allowed, over ``pp`` only."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.parallel.pipeline import pipeline_apply
    L, D = 8, 16
    stacked = {"w": jax.ShapeDtypeStruct((L, D, D), jnp.float32),
               "b": jax.ShapeDtypeStruct((L, D), jnp.float32)}
    x = jax.ShapeDtypeStruct((8, D), jnp.float32)

    def block_fn(layer, h):
        return jnp.tanh(h @ layer["w"] + layer["b"])

    def fn(p, xs):
        return pipeline_apply(block_fn, p, xs, mesh, num_microbatches=2)

    return fn, (stacked, x)


def _build_serve_lowprec(mesh):
    """The low-precision serve segment (docs/quantization.md): the same
    lone-JaxModel composite, int8w-quantized by the plan-level precision
    pass (``core/precision`` — bf16 activations, int8 per-channel
    weights dequantized inside the trace). Built through the SAME
    ``segment_composite`` builder the executor jits, with REAL init
    params (weight quantization needs concrete values for its max-abs
    scales). The contract is unchanged by the pass: ZERO manual
    collectives — dequant is pure elementwise math, and any tp
    resharding of the int8 weights stays GSPMD-inserted."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core import plan
    from mmlspark_tpu.core.precision import PrecisionPolicy
    from mmlspark_tpu.core.stage import ArrayMeta
    from mmlspark_tpu.models.bundle import ModelBundle
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import MLP

    d_in, width, n_out = 16, 32, 8
    module = MLP(features=(width,), num_outputs=n_out)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, d_in), jnp.float32))["params"]
    bundle = ModelBundle(module=module, params=params, input_spec=(d_in,),
                         output_names=("features", "logits"))
    jm = JaxModel(model=bundle, input_col="x", output_col="scores")
    seg = plan.collect_segment([jm], 0,
                               lambda c: ArrayMeta((d_in,), "float32"),
                               min_stages=1, mesh=mesh,
                               precision=PrecisionPolicy(mode="int8w"))
    composite, params_tuple = plan_segment_composite(seg)
    rows = plan.dp_rounded_minibatch(8, plan.mesh_dp(mesh), 8)
    entry = jax.ShapeDtypeStruct((rows, d_in), jnp.float32)
    return composite, (params_tuple, entry)


def _build_serve_decode(mesh):
    """The continuous-batching decode program (serve/generate.py): ONE
    fixed-shape ``[slots]`` token step over the slot-major KV cache,
    requests joining/leaving through the active mask. A DP replica owns
    its own slot table and cache, so the contract is ZERO manual
    collectives — a collective here would lockstep independent replicas'
    decode loops. Donation safety (the cache buffers return
    shape/dtype-identical, so ``donate_argnums=(0,)`` updates in place)
    is the other half of the contract; :func:`audit_stateful_spmd` and
    tests/test_spmd.py pin it on this same build."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models.sequence import TransformerTagger
    from mmlspark_tpu.serve.generate import build_decode_step

    S, L, H, T, hd = 4, 2, 2, 16, 8
    model = TransformerTagger(vocab_size=32, embed_dim=H * hd,
                              num_heads=H, num_layers=L, mlp_dim=32,
                              num_tags=32, max_len=T, causal=True)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
    step = build_decode_step(model)
    bufs = {"k": jax.ShapeDtypeStruct((S, L, H, T, hd), jnp.float32),
            "v": jax.ShapeDtypeStruct((S, L, H, T, hd), jnp.float32)}
    iv = jax.ShapeDtypeStruct((S,), jnp.int32)
    bv = jax.ShapeDtypeStruct((S,), jnp.bool_)
    return step, (bufs, params, iv, iv, bv, iv, bv)


def serve_decode_build(mesh: Any = None):
    """Public handle on the decode entry's build (what
    ``tests/test_spmd.py`` and the stateful audit reuse)."""
    return _build_serve_decode(mesh)


ENTRY_POINTS: tuple[EntryPoint, ...] = (
    EntryPoint("moe_apply", {"dp": 2, "ep": 4},
               ("dp", "fsdp", "ep"), _build_moe, capacity_dispatch=True),
    EntryPoint("pipeline_apply", {"dp": 2, "pp": 4},
               ("pp",), _build_pipeline),
    EntryPoint("ring_attention", {"dp": 2, "sp": 4},
               ("sp",), _build_ring),
    EntryPoint("ulysses_attention", {"dp": 2, "sp": 4},
               ("sp",), _build_ulysses),
    # the sharded serving entries (docs/serving.md): a DP replica's
    # single-chip segment, the same segment GSPMD-tp-sharded, and the
    # pipelined pp serve segment — the contracts ModelServer.add_model
    # audits a sharded load against
    EntryPoint("serve_dp_replica", {"dp": 1}, (), _build_serve_segment,
               expect_no_collectives=True),
    EntryPoint("serve_tp_segment", {"dp": 2, "tp": 4}, (),
               _build_serve_segment, expect_no_collectives=True),
    EntryPoint("serve_pp_segment", {"dp": 2, "pp": 4}, ("pp",),
               _build_serve_pp),
    # the int8w+bf16 quantized serve segments (docs/quantization.md):
    # the precision pass must not introduce collectives on a dp replica
    # nor communicate off-contract when the int8 weights tp-shard
    EntryPoint("serve_int8w_replica", {"dp": 1}, (),
               _build_serve_lowprec, expect_no_collectives=True),
    EntryPoint("serve_int8w_tp", {"dp": 2, "tp": 4}, (),
               _build_serve_lowprec, expect_no_collectives=True),
    # the continuous-batching token-serving decode step (PR 18,
    # serve/generate.py): one fixed-shape [slots] program over the
    # donated KV cache — a DP replica's decode loop must stay
    # manual-collective-free, like every other replica segment
    EntryPoint("serve_decode_replica", {"dp": 1}, (),
               _build_serve_decode, expect_no_collectives=True),
)


def verify_entry_point(ep: EntryPoint, devices: Any = None) -> SpmdReport:
    from mmlspark_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(ep.mesh_spec, devices)
    fn, args = ep.build(mesh)
    return verify_function(fn, *args, name=ep.name,
                           capacity_dispatch=ep.capacity_dispatch,
                           expect_axes=ep.expect_axes,
                           expect_no_collectives=ep.expect_no_collectives)


def verify_parallel_layer(devices: Any = None) -> dict[str, SpmdReport]:
    """Verify every declared parallel entry point; the repo gate expects
    every report clean. Needs ≥ 8 devices (the tier-1 CPU mesh)."""
    return {ep.name: verify_entry_point(ep, devices)
            for ep in ENTRY_POINTS}


# ---- the device-plan audit's multi-chip mode ----


@dataclasses.dataclass
class SegmentSpmdReport:
    """SPMD view of one fused device segment."""

    stages: list[str]
    entry_col: str
    entry_state: ShardState
    dp_extent: int
    minibatches: int | None
    schedule: CollectiveSchedule
    findings: list[SpmdFinding]

    def describe(self) -> str:
        names = "→".join(self.stages)
        mb = ("?" if self.minibatches is None else self.minibatches)
        return (f"device[{names}] entry {self.entry_col!r} "
                f"{self.entry_state.describe()} dp={self.dp_extent} "
                f"{mb} minibatch round(s), "
                f"{len(self.schedule.ops)} manual collective(s)")


@dataclasses.dataclass
class PlanSpmdAudit:
    """Multi-chip audit of a transform plan: per-segment shardings,
    dp-divisibility of the minibatch walk, and the (required-empty)
    manual collective schedule of each fused inference program."""

    segments: list[SegmentSpmdReport] = dataclasses.field(
        default_factory=list)
    findings: list[SpmdFinding] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def format(self) -> str:
        lines = [s.describe() for s in self.segments]
        if self.findings:
            lines.append(f"{len(self.findings)} finding(s):")
            lines.extend(f"  {f}" for f in self.findings)
        else:
            lines.append("no findings")
        return "\n".join(lines)


def plan_segment_composite(seg: Any) -> tuple[Callable, tuple]:
    """(composite fn, params tuple) for a fused plan segment — built by
    ``core.plan.segment_composite``, the SAME builder the executor jits.
    Shared by the multi-chip plan audit and the serve entry-point
    contracts so the verified program cannot drift from the dispatched
    one."""
    from mmlspark_tpu.core import plan

    return plan.segment_composite(seg, plan._segment_mesh(seg))


def audit_plan_spmd(stages: list, meta_of: Callable,
                    n_rows: int | None = None, mesh: Any = None,
                    expect_axes: Iterable[str] | None = None,
                    precision: Any = None) -> PlanSpmdAudit:
    """Replay the planner's segmentation (``core/plan.collect_segment``
    with the abstract ``meta_of`` probe — same contract as the PR 2 plan
    audit) and verify each fused segment's SPMD behavior on its
    inference mesh: batch sharded over the data axes, minibatch sizing
    divisible by the dp extent, and the collective contract.

    ``mesh`` pins the segments to an explicit mesh — the sharded-serving
    audit passes a replica's sub-mesh here (the same override
    ``serve``'s dispatch lanes pass to ``core.plan.transform_async``).
    ``expect_axes=None`` (the default, and the dp-replica contract)
    requires ZERO manual collectives in the composite; a tp/pp
    model-parallel serve segment instead passes its declared
    model-parallel axes, and any collective outside them (in particular
    over ``dp``) is a finding.

    ``precision`` pins the segments' low-precision policy
    (:mod:`mmlspark_tpu.core.precision`): the audit then traces the
    QUANTIZED composite — the same ``segment_composite`` builder the
    executor jits applies the pass, so a quantized serve load is
    verified against exactly the program it will dispatch."""
    import jax

    from mmlspark_tpu.core import plan
    from mmlspark_tpu.core.precision import PrecisionPolicy

    precision = PrecisionPolicy.parse(precision)
    audit = PlanSpmdAudit()
    i = 0
    while i < len(stages):
        # min_stages=1: serving dispatches even a LONE model stage
        # through the fused path (core/plan.transform_async), so the
        # audit must cover single-stage plans too — a lone JaxModel
        # with a manual collective must not audit as "no segments"
        seg = plan.collect_segment(stages, i, meta_of, min_stages=1,
                                   mesh=mesh, precision=precision)
        if seg is None:
            i += 1
            continue
        seg_mesh = plan._segment_mesh(seg)
        dp = plan.mesh_dp(seg_mesh)
        composite, params_tuple = plan_segment_composite(seg)
        size, _ = plan._segment_minibatch(seg)
        mb_rows = plan.dp_rounded_minibatch(size, dp, n_rows or size)
        entry = jax.ShapeDtypeStruct(
            (mb_rows,) + tuple(seg.entry_meta.shape),
            seg.entry_meta.dtype)
        name = "→".join(type(s).__name__ for s in seg.stages)
        report = verify_function(
            composite, params_tuple, entry, name=f"segment[{name}]",
            expect_axes=expect_axes,
            expect_no_collectives=expect_axes is None)
        # the executor shards minibatches P(('dp','fsdp')) on dim 0
        entry_state = ShardState((("dp", "fsdp"),) + ((),) * len(
            seg.entry_meta.shape))
        findings = list(report.findings)
        findings.extend(check_divisibility(
            entry_state, (mb_rows,) + tuple(seg.entry_meta.shape),
            dict(seg_mesh.shape), f"segment[{name}] minibatch"))
        minibatches = (plan.predict_segment_minibatches(seg, n_rows)
                       if n_rows else None)
        audit.segments.append(SegmentSpmdReport(
            [type(s).__name__ for s in seg.stages], seg.entry_col,
            entry_state, dp, minibatches, report.schedule, findings))
        audit.findings.extend(findings)
        i = seg.end
    return audit


def audit_stateful_spmd(step_fn: Callable, state_structs: Any,
                        args: tuple, name: str = "<stateful>",
                        expect_axes: Iterable[str] | None = None
                        ) -> SpmdReport:
    """SPMD audit of one stateful plan segment
    (:class:`~mmlspark_tpu.core.plan.StatefulSegment`): the multi-chip
    audit's coverage of programs that OWN device state across
    dispatches, which ``audit_plan_spmd``'s stateless segment replay
    cannot see.

    Two contracts, both static:

    * the usual collective contract — ``expect_axes=None`` (the
      dp-replica default) requires ZERO manual collectives
      (SPMD105), any declared axes bound communication (SPMD101);
    * **donation safety** (SPMD106): the step's returned state subtree
      must match the input state leaf-for-leaf in shape AND dtype, or
      ``donate_argnums=(0,)`` cannot alias the buffers in place — XLA
      silently falls back to a copy on CPU and refuses the donation on
      TPU, turning every token step into a full cache copy.
    """
    import jax

    report = verify_function(step_fn, state_structs, *args, name=name,
                             expect_axes=expect_axes,
                             expect_no_collectives=expect_axes is None)
    out = jax.eval_shape(step_fn, state_structs, *args)
    new_state = out[0] if isinstance(out, tuple) else out
    in_leaves, in_tree = jax.tree_util.tree_flatten(state_structs)
    out_leaves, out_tree = jax.tree_util.tree_flatten(new_state)
    mismatched = in_tree != out_tree or any(
        a.shape != b.shape or a.dtype != b.dtype
        for a, b in zip(in_leaves, out_leaves))
    if mismatched:
        report.findings.append(SpmdFinding(
            "SPMD106", name,
            "stateful step returns a state subtree that does not match "
            "the input state leaf-for-leaf (shape/dtype/structure): the "
            "donated buffers cannot be updated in place — every "
            "dispatch would copy the whole device state"))
    return report


# ---- the repo-wide gate ----

_FENCED_SOURCES = ("train/loop.py", "train/input.py", "serve/batcher.py",
                   "serve/mesh.py", "serve/generate.py")


def verify_repo(repo_root: str | None = None,
                devices: Any = None) -> dict:
    """The tier-1 gate: every parallel entry point verifies clean, and
    the multi-host train/serve sources keep the drain-fence discipline.
    Returns ``{"findings": [...], "reports": {...}, "fence_files": N}``.
    """
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    findings: list[SpmdFinding] = []
    reports = verify_parallel_layer(devices)
    for rep in reports.values():
        findings.extend(rep.findings)
    n_fence = 0
    for rel in _FENCED_SOURCES:
        path = os.path.join(repo_root, "mmlspark_tpu",
                            rel.replace("/", os.sep))
        if not os.path.exists(path):
            continue
        with open(path, "r", encoding="utf-8") as fh:
            findings.extend(check_fence_discipline(fh.read(), rel))
        n_fence += 1
    return {"findings": findings, "reports": reports,
            "fence_files": n_fence}

"""Abstract column/table values for the pre-flight pipeline analyzer.

The reference rejects broken pipelines before any data moves by running
``transformSchema`` over a ``StructType`` (reference: every stage's
``transformSchema``, core/schema SparkSchema/SchemaConstants). The analog
here is a :class:`TableSchema`: an ordered map of column name →
:class:`ColumnInfo` abstract value (kind, dtype, per-row shape, sidecar
metadata) that stages transform via their ``infer_schema`` hook with **no
data and no device execution**. The image-struct and categorical contracts
from :mod:`mmlspark_tpu.core.schema` are first-class kinds, and
:meth:`TableSchema.entry_meta` mirrors the pipeline planner's concrete
entry probe (``core/plan._entry_meta``) so the device-plan audit predicts
exactly what the executor would do.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Mapping

import numpy as np

from mmlspark_tpu.core.stage import ArrayMeta

# column kinds — the abstract analog of the host table's cell types
KIND_SCALAR = "scalar"      # one number per row (numeric numpy column)
KIND_VECTOR = "vector"      # fixed-or-ragged numeric vector per row
KIND_IMAGE = "image"        # image-struct dicts (HWC data + dims + path)
KIND_TEXT = "text"          # one string per row
KIND_TOKENS = "tokens"      # list-of-str per row (pre-tokenized text)
KIND_DATE = "date"          # datetime cells
KIND_OBJECT = "object"      # other python objects (bytes, dicts, ...)
KIND_UNKNOWN = "unknown"    # nothing provable (e.g. behind an opaque UDF)


class SchemaError(Exception):
    """A pipeline-contract violation found by schema inference.

    Raised by a stage's ``infer_schema`` when the incoming schema cannot
    legally feed the stage (missing column, image where a vector is
    required, size mismatch into a model, ...). The analyzer converts it
    into a stage-indexed diagnostic and continues with a degraded schema.
    """

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


@dataclasses.dataclass
class ColumnInfo:
    """What is statically known about one column.

    ``shape`` is the per-row shape; entries may be ``None`` for dims that
    vary or are unknown (a ragged image column is ``kind=image`` with a
    partial shape). ``meta`` carries the sidecar schema (categorical
    levels, score roles, the image flag) exactly as
    ``DataTable.meta[col]`` would at runtime.
    """

    kind: str = KIND_UNKNOWN
    dtype: str | None = None
    shape: tuple | None = None
    has_missing: bool = False
    meta: dict = dataclasses.field(default_factory=dict)

    # -- constructors --

    @staticmethod
    def scalar(dtype: str = "float64", **kw: Any) -> "ColumnInfo":
        return ColumnInfo(KIND_SCALAR, dtype, (), **kw)

    @staticmethod
    def vector(size: int | None, dtype: str = "float32",
               **kw: Any) -> "ColumnInfo":
        return ColumnInfo(KIND_VECTOR, dtype, (size,), **kw)

    @staticmethod
    def image(height: int | None, width: int | None, channels: int | None = 3,
              dtype: str = "uint8", **kw: Any) -> "ColumnInfo":
        from mmlspark_tpu.core.schema import SchemaConstants
        info = ColumnInfo(KIND_IMAGE, dtype, (height, width, channels), **kw)
        info.meta.setdefault(SchemaConstants.K_IMAGE, True)
        return info

    @staticmethod
    def text(**kw: Any) -> "ColumnInfo":
        return ColumnInfo(KIND_TEXT, "str", (), **kw)

    @staticmethod
    def tokens(**kw: Any) -> "ColumnInfo":
        return ColumnInfo(KIND_TOKENS, "str", None, **kw)

    @staticmethod
    def unknown(**kw: Any) -> "ColumnInfo":
        return ColumnInfo(KIND_UNKNOWN, **kw)

    # -- derived properties --

    @property
    def concrete_shape(self) -> tuple | None:
        """The per-row shape when fully known, else None."""
        if self.shape is None or any(d is None for d in self.shape):
            return None
        return tuple(int(d) for d in self.shape)

    @property
    def row_size(self) -> int | None:
        """Number of scalar values per row when provable (vector length,
        image h*w*c, 1 for scalars), else None."""
        s = self.concrete_shape
        if s is None:
            return None
        return int(np.prod(s)) if s else 1

    def copy(self) -> "ColumnInfo":
        return dataclasses.replace(self, shape=self.shape,
                                   meta=dict(self.meta))

    def summary(self) -> tuple:
        """(kind, dtype, shape) — the comparison form used by tests that
        hold predictions against observed execution."""
        return (self.kind, self.dtype,
                None if self.shape is None else tuple(self.shape))


def require_image_input(schema: "TableSchema", col: str, stage_name: str
                        ) -> ColumnInfo:
    """Shared ``infer_schema`` preamble for image-consuming stages: the
    column must exist (unknown is tolerated when the schema is inexact)
    and must not be a provably non-image kind — the image-vs-vector
    confusion check, defined once so the acceptance set cannot drift
    between stages. Returns the column's info (or unknown)."""
    info = schema.get(col)
    if info is None:
        if schema.exact:
            raise SchemaError(
                "missing-input-column",
                f"{stage_name} reads missing column {col!r}; "
                f"available: {list(schema)}")
        return ColumnInfo.unknown()
    # image structs or raw encoded bytes both qualify; only a provably
    # different kind is a contract violation
    if info.kind not in (KIND_IMAGE, KIND_OBJECT, KIND_UNKNOWN):
        raise SchemaError(
            "image-column-expected",
            f"{stage_name} input {col!r} is a {info.kind} column; "
            "it needs an image-struct (or encoded bytes) column")
    return info


def _info_from_cells(cells: Iterable[Any], meta: Mapping[str, Any]
                     ) -> ColumnInfo:
    """Classify an object column's cells (the concrete→abstract direction,
    used by :meth:`TableSchema.from_table`)."""
    from datetime import datetime

    from mmlspark_tpu.core.schema import SchemaConstants
    from mmlspark_tpu.data.table import IMAGE_FIELDS, is_missing

    has_missing = False
    first = None
    shapes: set[tuple] = set()
    dtypes: set[str] = set()
    kind = None
    for v in cells:
        if is_missing(v):
            has_missing = True
            continue
        if first is None:
            first = v
        if isinstance(v, dict) and set(IMAGE_FIELDS).issubset(v.keys()):
            kind = kind or KIND_IMAGE
            if kind == KIND_IMAGE:
                d = np.asarray(v["data"])
                shape = d.shape if d.ndim == 3 else d.shape + (1,)
                shapes.add(tuple(int(x) for x in shape))
                dtypes.add(str(d.dtype))
            continue
        if isinstance(v, str):
            kind = kind if kind not in (None, KIND_TEXT) else KIND_TEXT
            continue
        if isinstance(v, datetime):
            kind = kind if kind not in (None, KIND_DATE) else KIND_DATE
            continue
        if isinstance(v, (np.ndarray, list, tuple)):
            seq_kind = (KIND_TOKENS if len(v) and isinstance(v[0], str)
                        else KIND_VECTOR)
            kind = kind if kind not in (None, seq_kind) else seq_kind
            if kind == KIND_VECTOR:
                a = np.asarray(v)
                shapes.add((int(a.size),))
                dtypes.add(str(a.dtype))
            continue
        if isinstance(v, (bool, int, float, np.number, np.bool_)):
            kind = kind if kind not in (None, KIND_SCALAR) else KIND_SCALAR
            shapes.add(())
            dtypes.add(str(np.asarray(v).dtype))
            continue
        kind = KIND_OBJECT
    if first is None:
        return ColumnInfo(KIND_UNKNOWN, has_missing=has_missing,
                          meta=dict(meta))
    if meta.get(SchemaConstants.K_IMAGE) and kind is None:
        kind = KIND_IMAGE
    kind = kind or KIND_OBJECT
    shape = shapes.pop() if len(shapes) == 1 else None
    dtype = dtypes.pop() if len(dtypes) == 1 else None
    if kind in (KIND_TEXT, KIND_DATE):
        shape, dtype = (), ("str" if kind == KIND_TEXT else "datetime")
    elif kind in (KIND_TOKENS, KIND_OBJECT):
        shape, dtype = None, None
    return ColumnInfo(kind, dtype, shape, has_missing=has_missing,
                      meta=dict(meta))


class TableSchema:
    """Ordered column-name → :class:`ColumnInfo` map — the abstract table.

    ``exact`` is True while the column set is provably complete; an opaque
    stage the analyzer cannot interpret flips it to False, after which
    missing-input findings downgrade to warnings (the column may exist).
    Stages' ``infer_schema`` hooks treat schemas as immutable: derive with
    :meth:`copy` / :meth:`with_column` / :meth:`drop`.
    """

    def __init__(self, columns: Mapping[str, ColumnInfo] | None = None,
                 exact: bool = True):
        self.columns: dict[str, ColumnInfo] = dict(columns or {})
        self.exact = exact
        # non-fatal findings attached by infer_schema hooks; the analyzer
        # drains these into stage-indexed diagnostics after each stage
        self.pending: list[tuple[str, str, str]] = []

    # -- mapping surface --

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def __iter__(self):
        return iter(self.columns)

    def column(self, name: str) -> ColumnInfo:
        if name not in self.columns:
            raise SchemaError(
                "missing-input-column",
                f"no column {name!r}; available: {list(self.columns)}")
        return self.columns[name]

    def get(self, name: str) -> ColumnInfo | None:
        return self.columns.get(name)

    # -- functional updates --

    def copy(self) -> "TableSchema":
        out = TableSchema({k: v.copy() for k, v in self.columns.items()},
                          exact=self.exact)
        # pending findings ride along so nested folds (Pipeline inside
        # Pipeline) surface inner-stage warnings at the outer walk
        out.pending = list(self.pending)
        return out

    def with_column(self, name: str, info: ColumnInfo) -> "TableSchema":
        out = self.copy()
        out.columns[name] = info
        return out

    def drop(self, *names: str) -> "TableSchema":
        out = self.copy()
        for n in names:
            out.columns.pop(n, None)
        return out

    def as_inexact(self) -> "TableSchema":
        out = self.copy()
        out.exact = False
        return out

    def warn(self, code: str, message: str, severity: str = "warning"
             ) -> None:
        """Attach a non-fatal finding for the analyzer to collect."""
        self.pending.append((severity, code, message))

    # -- construction --

    @staticmethod
    def from_table(table: Any) -> "TableSchema":
        """Derive the abstract schema of a concrete DataTable (scans cells
        once on host; no device interaction). The observed-schema direction
        used to validate predictions against real execution."""
        cols: dict[str, ColumnInfo] = {}
        for name in table.columns:
            arr = table[name]
            meta = dict(table.column_meta(name))
            if arr.dtype != object:
                if np.issubdtype(arr.dtype, np.str_):
                    cols[name] = ColumnInfo(KIND_TEXT, "str", (), meta=meta)
                elif arr.ndim == 1:
                    has_nan = bool(
                        np.issubdtype(arr.dtype, np.floating)
                        and np.isnan(arr).any())
                    cols[name] = ColumnInfo(
                        KIND_SCALAR, str(arr.dtype), (),
                        has_missing=has_nan, meta=meta)
                else:
                    cols[name] = ColumnInfo(KIND_VECTOR, str(arr.dtype),
                                            (int(arr.shape[1]),), meta=meta)
            else:
                cols[name] = _info_from_cells(arr, meta)
        return TableSchema(cols)

    @staticmethod
    def from_spec(spec: Mapping[str, Any]) -> "TableSchema":
        """Build a schema from a JSON-friendly dict (the CLI input form)::

            {"image": {"kind": "image", "shape": [32, 32, 3]},
             "age":   {"kind": "scalar", "dtype": "float64"},
             "text":  "text"}

        A bare string value is shorthand for ``{"kind": <value>}``.
        """
        cols: dict[str, ColumnInfo] = {}
        for name, entry in spec.items():
            if isinstance(entry, str):
                entry = {"kind": entry}
            kind = entry.get("kind", KIND_UNKNOWN)
            shape = entry.get("shape")
            if shape is not None:
                shape = tuple(None if d is None else int(d) for d in shape)
            elif kind in (KIND_SCALAR, KIND_TEXT, KIND_DATE):
                shape = ()
            dtype = entry.get("dtype")
            if dtype is None:
                dtype = {KIND_IMAGE: "uint8", KIND_VECTOR: "float32",
                         KIND_SCALAR: "float64", KIND_TEXT: "str",
                         KIND_DATE: "datetime"}.get(kind)
            info = ColumnInfo(kind, dtype, shape,
                              has_missing=bool(entry.get("has_missing")),
                              meta=dict(entry.get("meta") or {}))
            if kind == KIND_IMAGE:
                from mmlspark_tpu.core.schema import SchemaConstants
                info.meta.setdefault(SchemaConstants.K_IMAGE, True)
            cols[name] = info
        return TableSchema(cols)

    # -- the planner-facing view --

    def entry_meta(self, name: str) -> ArrayMeta | None:
        """The :class:`ArrayMeta` the pipeline planner's entry coercion
        would produce for this column, or None when coercion would decline
        (mirrors ``core/plan._entry_meta`` + the strict `_coerce_entry`
        rules: missing rows, ragged shapes, and non-numeric data all fall
        back to the host path)."""
        info = self.columns.get(name)
        if info is None or info.has_missing:
            return None
        if info.kind == KIND_IMAGE:
            shape = info.concrete_shape
            if info.dtype != "uint8" or shape is None or len(shape) != 3:
                return None
            return ArrayMeta(shape, "uint8", is_image=True)
        if info.kind == KIND_VECTOR:
            size = info.row_size
            if size is None:
                return None
            dt = "uint8" if info.dtype == "uint8" else "float32"
            return ArrayMeta((size,), dt)
        if info.kind == KIND_SCALAR and info.dtype is not None:
            if not np.issubdtype(np.dtype(info.dtype), np.number):
                return None
            return ArrayMeta((1,), "float32")
        return None

    # -- presentation --

    def summary(self) -> dict[str, tuple]:
        return {k: v.summary() for k, v in self.columns.items()}

    def empty_table(self) -> Any:
        """A 0-row DataTable realizing this schema — the probe the analyzer
        feeds to opaque UDF stages (LambdaTransformer) so their column
        effects are observed without touching real data."""
        from mmlspark_tpu.data.table import DataTable
        cols = {}
        for name, info in self.columns.items():
            if info.kind == KIND_SCALAR and info.dtype not in (None, "str",
                                                               "datetime"):
                cols[name] = np.empty(0, dtype=np.dtype(info.dtype))
            else:
                cols[name] = np.empty(0, dtype=object)
        return DataTable(cols, {k: dict(v.meta)
                                for k, v in self.columns.items() if v.meta})

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{k}:{v.kind}" + (f"{list(v.shape)}" if v.shape else "")
            for k, v in self.columns.items())
        return f"TableSchema[{cols}]{'' if self.exact else ' (inexact)'}"

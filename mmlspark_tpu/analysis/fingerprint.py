"""Static compile-cache fingerprints — know the key without compiling.

The persistent AOT compile cache (core/compile_cache.py) keys programs
by a plan fingerprint. That fingerprint is *statically derivable*: it
needs only the stages' content identities and the schema's entry
layout — no data, no device dispatch, no XLA. This module exposes that
derivation at the analysis layer, so pre-flight tooling can answer
"which cache entries will this pipeline want?" (and ops tooling can
pre-seed or audit a fleet cache dir) by replaying the SAME segment
planning the executor uses — :func:`core.plan.collect_segment` over an
abstract :class:`~mmlspark_tpu.analysis.info.TableSchema` — exactly
the way the SPMD auditor replays it.
"""

from __future__ import annotations

from typing import Any


def plan_fingerprints(stages: Any, schema: Any, mesh: Any = None,
                      precision: Any = None) -> list[str | None]:
    """The compile-cache fingerprint of every device segment the plan
    would form over ``schema``, in segment order. ``None`` entries mark
    segments that cannot be fingerprinted (a stage without a stable
    content identity — those compile in memory). ``stages`` is a stage
    list or anything with ``.stages``; ``mesh`` and ``precision``
    match the serving configuration being asked about.

    Purely static: nothing compiles, uploads, or touches devices
    beyond jax's backend enumeration for the version/platform fields.
    """
    from mmlspark_tpu.core import compile_cache as _cc
    from mmlspark_tpu.core.plan import _segment_mesh, collect_segment
    from mmlspark_tpu.core.precision import PrecisionPolicy
    inner = getattr(stages, "stages", None)
    if inner is not None and not callable(inner):
        stages = list(inner)
    policy = PrecisionPolicy.parse(precision)
    if policy is not None and not policy.active:
        policy = None
    out: list[str | None] = []
    i = 0
    while i < len(stages):
        seg = collect_segment(stages, i, schema.entry_meta, min_stages=1,
                              mesh=mesh, precision=policy)
        if seg is None:
            i += 1
            continue
        # resolve the mesh the way the executor will (stage-declared /
        # default when no override) so the static fingerprint IS the
        # runtime cache key, not an approximation of it
        out.append(_cc.plan_fingerprint(seg.stages, seg.entry_meta,
                                        mesh=_segment_mesh(seg),
                                        precision=seg.precision))
        i = seg.end
    return out

"""Generate runnable sample notebooks from the examples.

The reference ships its demo surface as notebooks and executes them in CI
(reference: notebooks/samples/, tools/notebook/tester/
NotebookTestSuite.py:13-60). Here the single source of truth is
``examples/*.py`` (CI-executed scripts); this tool derives the notebook
form deterministically so the two can never drift:

* the module docstring becomes the title/markdown cell,
* top-level code splits into cells at double-blank-line boundaries (the
  PEP-8 seam between top-level definitions),
* the ``if __name__ == "__main__"`` guard stays — notebook kernels run
  with ``__name__ == "__main__"``, so the notebook executes exactly the
  script's entry path.

``tests/test_notebooks.py`` regenerates the set to assert freshness and
executes every notebook through a real kernel (nbclient) in the full CI
lane; the Docker image COPYs ``notebooks/`` so its jupyter entry opens
these.

Usage: python -m mmlspark_tpu.tools.make_notebooks [out_dir]
"""

from __future__ import annotations

import ast
import os
import sys

EXAMPLE_TITLES = {
    "tabular_classification_101": "101 - Tabular Classification",
    "flight_delay_regression_102": "102 - Regression with TrainRegressor",
    "before_after_103": "103 - Pipelines Before and After",
    "book_reviews_text_201": "201 - Text Featurization",
    "book_reviews_word2vec_202": "202 - Word2Vec Embeddings",
    "cifar_eval_301": "301 - CIFAR-10 CNN Evaluation",
    "image_transforms_302": "302 - Image Transforms",
    "transfer_learning_303": "303 - Transfer Learning",
    "medical_entity_304": "304 - Medical Entity Extraction",
    "flowers_featurizer_305": "305 - Flowers Featurization",
    "distributed_finetune_306": "306 - Distributed Training",
}


def _split_cells(source: str) -> list[str]:
    """Split top-level code at 2+ blank-line seams (PEP-8 boundaries),
    keeping multi-line statements intact (the seam must sit at depth 0)."""
    lines = source.split("\n")
    # depth-0 line index set via ast: any line inside a top-level node's
    # span is not a seam
    tree = ast.parse(source)
    covered = set()
    for node in tree.body:
        end = getattr(node, "end_lineno", node.lineno)
        covered.update(range(node.lineno, end + 1))
    cells: list[list[str]] = [[]]
    blanks = 0
    for i, line in enumerate(lines, start=1):
        if not line.strip() and i not in covered:
            blanks += 1
            if blanks >= 2 and cells[-1]:
                cells.append([])
                blanks = 0
            continue
        if line.strip():
            blanks = 0
        cells[-1].append(line)
    return ["\n".join(c).strip("\n") for c in cells if "".join(c).strip()]


def make_notebook(example_path: str):
    import nbformat

    with open(example_path) as f:
        source = f.read()
    tree = ast.parse(source)
    doc = ast.get_docstring(tree) or ""
    # strip the docstring node from the code body
    body_start = 0
    if (tree.body and isinstance(tree.body[0], ast.Expr)
            and isinstance(tree.body[0].value, ast.Constant)):
        body_start = tree.body[0].end_lineno
    code = "\n".join(source.split("\n")[body_start:]).strip("\n")

    stem = os.path.splitext(os.path.basename(example_path))[0]
    title = EXAMPLE_TITLES.get(stem, stem)
    nb = nbformat.v4.new_notebook()
    nb.metadata["kernelspec"] = {"name": "python3",
                                 "display_name": "Python 3",
                                 "language": "python"}
    md = f"# {title}\n\n" + doc + (
        f"\n\n*Generated from `examples/{stem}.py` by "
        "`mmlspark_tpu.tools.make_notebooks` — edit the example, then "
        "regenerate.*")
    nb.cells.append(nbformat.v4.new_markdown_cell(md))
    for cell_src in _split_cells(code):
        nb.cells.append(nbformat.v4.new_code_cell(cell_src))
    # deterministic cell ids (nbformat defaults to random ones) keep
    # regeneration byte-stable — adding an example must not churn the
    # other committed notebooks
    for i, cell in enumerate(nb.cells):
        cell["id"] = f"{stem}-{i}"
    return stem, title, nb


def build(out_dir: str, examples_dir: str | None = None) -> list[str]:
    import nbformat

    examples_dir = examples_dir or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "examples")
    os.makedirs(out_dir, exist_ok=True)
    written = []
    for fname in sorted(os.listdir(examples_dir)):
        if not fname.endswith(".py"):
            continue
        stem, title, nb = make_notebook(os.path.join(examples_dir, fname))
        path = os.path.join(out_dir, f"{title}.ipynb")
        nbformat.write(nb, path)
        written.append(path)
    return written


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    out = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "notebooks", "samples")
    for p in build(out):
        print(p)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Multi-process ``jax.distributed`` job launcher — the cluster-install /
``mml-exec`` analog.

The reference installs itself onto a Spark cluster via an HDInsight script
action and launches work through a shell wrapper (reference:
tools/hdi/install-mmlspark.sh, tools/bin/mml-exec:1-50); its multi-node MPI
launcher was a never-wired stub
(cntk-train/src/main/scala/CommandBuilders.scala:95-117). The TPU-native
equivalent is one coordinator + N ``jax.distributed`` worker processes:

``local`` mode (default) starts all N workers on THIS host — the smoke/dev
path, and exactly how the multi-host test suite runs. ``pod`` mode execs
the command once with only the coordinator env set, for running under an
external per-host scheduler (GKE/xmanager/`gcloud compute tpus tpus-vm ssh
--worker=all`), where each TPU-VM worker invokes the same command and JAX
discovers its process id from the TPU runtime.

Worker wiring is environment-based (read back by
``mmlspark_tpu.utils.env.distributed_init``):

* ``MMLSPARK_TPU_COORDINATOR``    — host:port of process 0
* ``MMLSPARK_TPU_NUM_PROCESSES``  — world size
* ``MMLSPARK_TPU_PROCESS_ID``     — this worker's rank (local mode)

Failure semantics (SURVEY §5 failure detection): the launcher watches all
workers; the first nonzero exit terminates the rest (grace period, then
kill) and the launcher exits with that worker's code — a died worker can
never leave the remaining ranks silently hung inside a collective.
Combined with ``TrainConfig.checkpoint_dir`` the restart path is: rerun
the same launch command and training resumes from the last checkpoint.

Usage::

    python -m mmlspark_tpu.tools.launch -n 4 -- python train_job.py
    python -m mmlspark_tpu.tools.launch -n 4 --cpu-devices 2 -- \\
        python tests/multihost_worker.py        # CPU-mesh simulation
    python -m mmlspark_tpu.tools.launch --mode pod \\
        --coordinator tpu-host-0:8476 -- python train_job.py
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import IO, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _pump(stream: IO[str], rank: int, out: IO[str], tail: list[str]) -> None:
    """Prefix a worker's merged stdout/stderr with its rank; keep a tail
    ring for the failure report."""
    for line in stream:
        tail.append(line)
        if len(tail) > 40:
            del tail[0]
        out.write(f"[worker {rank}] {line}")
        out.flush()


# coordinator-bind failures that justify retrying on a fresh port: the
# _free_port() probe closes its socket before worker 0 binds it (TOCTOU —
# another process can grab it in between, e.g. parallel CI launches)
_BIND_RETRY_MARKERS = ("already in use", "failed to bind", "errno 98",
                       "eaddrinuse")  # matched case-insensitively


def launch_local(cmd: Sequence[str], num_processes: int,
                 coordinator: str | None = None,
                 cpu_devices: int | None = None,
                 grace_seconds: float = 10.0,
                 extra_env: dict[str, str] | None = None,
                 port_retries: int = 3) -> int:
    """Start ``num_processes`` copies of ``cmd`` on this host and wait.

    Returns the exit code: 0 if every worker succeeded, else the first
    failing worker's code (the rest are terminated). The reference's only
    failure handling was an exit-code check on the single external CNTK
    process (cntk-train/src/main/scala/CNTKLearner.scala:147-151); here the
    check spans the whole worker set. When the coordinator port was
    auto-picked, a coordinator bind failure retries the whole launch on a
    fresh port (advisor round 4: the free-port probe is racy)."""
    auto_port = coordinator is None
    attempts = max(1, port_retries) if auto_port else 1
    for attempt in range(attempts):
        code, bind_failed = _launch_local_once(
            cmd, num_processes, coordinator or f"localhost:{_free_port()}",
            cpu_devices, grace_seconds, extra_env)
        if code == 0 or not (auto_port and bind_failed):
            return code
        if attempt + 1 < attempts:
            sys.stderr.write(
                f"coordinator bind failed (attempt {attempt + 1}/"
                f"{attempts}); retrying on a fresh port\n")
    return code


def _launch_local_once(cmd: Sequence[str], num_processes: int,
                       coordinator: str,
                       cpu_devices: int | None = None,
                       grace_seconds: float = 10.0,
                       extra_env: dict[str, str] | None = None
                       ) -> tuple[int, bool]:
    """One launch attempt; returns (exit_code, coordinator_bind_failed)."""
    procs: list[subprocess.Popen] = []
    tails: list[list[str]] = []
    threads = []
    for rank in range(num_processes):
        env = dict(os.environ)
        env.update(extra_env or {})
        env["MMLSPARK_TPU_COORDINATOR"] = coordinator
        env["MMLSPARK_TPU_NUM_PROCESSES"] = str(num_processes)
        env["MMLSPARK_TPU_PROCESS_ID"] = str(rank)
        if cpu_devices:
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{cpu_devices}").strip()
        p = subprocess.Popen(list(cmd), env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True,
                             errors="replace")
        tail: list[str] = []
        t = threading.Thread(target=_pump, args=(p.stdout, rank, sys.stdout,
                                                 tail), daemon=True)
        t.start()
        procs.append(p)
        tails.append(tail)
        threads.append(t)

    failed_rank: int | None = None
    seen_done: set[int] = set()
    try:
        while True:
            codes = [p.poll() for p in procs]
            # attribute failure to the FIRST worker observed dead across
            # polls, not the lowest rank in this poll — when a crash takes
            # peers down with it (jax.distributed aborting on a lost
            # coordinator), the root cause is the earliest exit, and rank
            # order would misreport a consequential death as the cause
            for rank, code in enumerate(codes):
                if code is not None and rank not in seen_done:
                    seen_done.add(rank)
                    if code != 0 and failed_rank is None:
                        failed_rank = rank
            if failed_rank is not None or all(c == 0 for c in codes):
                break
            time.sleep(0.2)
    except KeyboardInterrupt:
        failed_rank = -1
    if failed_rank is not None:
        # first failure (or interrupt): give survivors a grace period to
        # notice the lost peer (jax.distributed heartbeats), then kill —
        # never leave ranks hung inside a dead collective
        deadline = time.time() + grace_seconds
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.2)
            if p.poll() is None:
                p.kill()
    for p in procs:
        p.wait()
    for t in threads:
        t.join(timeout=2.0)
    if failed_rank is not None and failed_rank >= 0:
        code = procs[failed_rank].returncode
        tail_text = "".join(tails[failed_rank])
        sys.stderr.write(
            f"worker {failed_rank} exited with code {code}; last output:\n"
            + "".join(f"  {ln}" for ln in tails[failed_rank][-15:]))
        low = tail_text.lower()
        bind_failed = any(m in low for m in _BIND_RETRY_MARKERS)
        return code or 1, bind_failed
    if failed_rank == -1:
        return 130, False
    return 0, False


def launch_pod(cmd: Sequence[str], coordinator: str | None,
               num_processes: int | None) -> int:
    """Exec the command for THIS pod worker: set the coordinator env (rank
    and world size come from the TPU runtime via JAX auto-discovery unless
    given) and replace the current process."""
    env = dict(os.environ)
    if coordinator:
        env["MMLSPARK_TPU_COORDINATOR"] = coordinator
    if num_processes:
        env["MMLSPARK_TPU_NUM_PROCESSES"] = str(num_processes)
    os.execvpe(cmd[0], list(cmd), env)


def main(argv: Sequence[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mmlspark-tpu-launch",
        description="Launch an N-process jax.distributed job "
                    "(see module docstring)")
    ap.add_argument("-n", "--num-processes", type=int, default=None,
                    help="world size (required in local mode)")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (local default: a free "
                         "localhost port)")
    ap.add_argument("--mode", choices=("local", "pod"), default="local")
    ap.add_argument("--cpu-devices", type=int, default=None,
                    help="local mode: give each worker this many virtual "
                         "CPU devices (JAX_PLATFORMS=cpu + "
                         "xla_force_host_platform_device_count) — the "
                         "hardware-free simulation rig")
    ap.add_argument("--grace-seconds", type=float, default=10.0,
                    help="after a worker fails, seconds before survivors "
                         "are killed")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no worker command given (append: -- python worker.py ...)")
    if args.mode == "pod":
        return launch_pod(cmd, args.coordinator, args.num_processes)
    if not args.num_processes or args.num_processes < 1:
        ap.error("--num-processes is required in local mode")
    return launch_local(cmd, args.num_processes, args.coordinator,
                        args.cpu_devices, args.grace_seconds)


if __name__ == "__main__":
    raise SystemExit(main())

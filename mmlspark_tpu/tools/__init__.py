"""Command-line tools shipped with the package (zoo publishing, doc
generation entry points). The packaging analog of the reference's
``tools/`` scripts that ship with the built artifacts."""

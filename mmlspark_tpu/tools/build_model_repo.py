"""Build a local pretrained-model repository (the zoo-publishing tool).

The reference serves pretrained CNTK models from an Azure CDN manifest
(reference: ModelDownloader.scala:184-186; Schema.scala:54-74 records each
model's dataset provenance). This environment has no egress, so the
equivalent is a reproducible local repository built from data available
in-image:

* image models (ConvNet / ResNet / ViT families) train on **real data** —
  scikit-learn's handwritten-digits set upscaled to 32×32 RGB — to
  genuinely good held-out accuracy, which is **measured and recorded in
  the manifest** (``eval_metric``/``eval_value``),
* the BiLSTM tagger trains on a deterministic synthetic tagging rule,
  with held-out token accuracy recorded the same way,
* the full-size ResNet50 / ViT_B16 entries are size stand-ins (real
  pretraining needs data egress); their manifests say so (dataset
  ``synthetic-standin``) rather than implying capability.

Usage:
    mmlspark-tpu-build-repo <repo_dir> [--scale small|full]
    (or: python -m mmlspark_tpu.tools.build_model_repo <repo_dir>)

``small`` (default) publishes CI-scale models in under two minutes;
``full`` also publishes ResNet50 / ResNet50_Infer (the folded frozen-BN
serving variant) / ViT_B16 at real parameter count.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def digits_rgb32() -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Real image data without egress: sklearn digits (1797 8×8 grayscale)
    upscaled ×4 to 32×32 and tiled to RGB, pixel range 0-255. Deterministic
    80/20 split shared by the publisher, the examples, and the tests, so
    every recorded accuracy is honest held-out accuracy."""
    try:
        from sklearn.datasets import load_digits
    except ImportError as e:  # same convention as ml/learners._require_sklearn
        raise ImportError(
            "building the model repository trains on scikit-learn's digits "
            "dataset — pip install scikit-learn (or mmlspark-tpu[trees])"
        ) from e

    d = load_digits()
    x8 = d.images.astype(np.float32) * (255.0 / 16.0)       # [N, 8, 8]
    x32 = np.kron(x8, np.ones((1, 4, 4), np.float32))       # [N, 32, 32]
    x = np.repeat(x32[..., None], 3, axis=-1)               # [N, 32, 32, 3]
    y = d.target.astype(np.int64)
    order = np.random.default_rng(0).permutation(len(x))
    x, y = x[order], y[order]
    split = int(0.8 * len(x))
    return x[:split], y[:split], x[split:], y[split:]


def _train_eval(bundle, xtr, ytr, xte, yte, steps: int = 300,
                bs: int = 128, lr: float = 1e-3):
    """Train with Adam on (xtr, ytr), measure held-out accuracy on
    (xte, yte); returns (bundle, accuracy). Training runs through the same
    preprocessing the scoring path applies, so downloaded weights behave
    identically under ``JaxModel``."""
    import jax
    import jax.numpy as jnp
    import optax

    from mmlspark_tpu.models.bundle import PREPROCESSORS

    tx = optax.adam(lr)
    opt = tx.init(bundle.params)
    params = bundle.params
    pre = PREPROCESSORS.get(bundle.preprocess) if bundle.preprocess else None

    def logits_fn(p, xb):
        if pre is not None:
            xb = pre(xb)
        return bundle.module.apply({"params": p}, xb, output="logits")

    def loss_fn(p, xb, yb):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits_fn(p, xb), yb).mean()

    @jax.jit
    def step(p, o, xb, yb):
        l, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), o, l

    bs = min(bs, len(xtr))
    r = np.random.default_rng(0)
    first = last = None
    order = None
    per_epoch = max(1, len(xtr) // bs)
    for i in range(steps):
        if i % per_epoch == 0:
            order = r.permutation(len(xtr))
        s = (i % per_epoch) * bs
        idx = order[s:s + bs]
        params, opt, l = step(params, opt, xtr[idx], ytr[idx])
        # keep device scalars; resolve after the loop (a float() here
        # blocks the host on every step — JX105)
        if first is None:
            first = l
        last = l
    first, last = float(first), float(last)

    jeval = jax.jit(logits_fn)
    preds = []
    for s in range(0, len(xte), 256):
        preds.append(np.asarray(jeval(params, xte[s:s + 256])).argmax(-1))
    acc = float((np.concatenate(preds) == yte).mean())
    print(f"  {bundle.name}: loss {first:.3f} -> {last:.3f} "
          f"({steps} steps), held-out accuracy {acc:.3f}")
    bundle.params = params
    return bundle, acc


def _train_bn_and_fold(xtr, ytr, xte, yte, steps: int = 200, bs: int = 128,
                       lr: float = 1e-3):
    """The reference-parity zoo flow: train a *BatchNorm* ResNet (the
    reference zoo's ResNet-50 is a BN network, Schema.scala:54-74), then
    fold the frozen statistics into the conv weights at publish time
    (models/resnet.py:fold_batchnorm) and publish the norm-free inference
    bundle. The recorded accuracy is measured on the FOLDED net — the
    artifact users download."""
    import jax
    import jax.numpy as jnp
    import optax

    from mmlspark_tpu.models.bundle import PREPROCESSORS, ModelBundle
    from mmlspark_tpu.models.resnet import fold_batchnorm, resnet18_thin

    module = resnet18_thin(norm="batch")
    pre = PREPROCESSORS["imagenet_norm"]
    variables = module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 32, 32, 3), jnp.float32))
    params, stats = variables["params"], variables["batch_stats"]
    tx = optax.adam(lr)
    opt = tx.init(params)

    def loss_fn(p, st, xb, yb):
        logits, new_state = module.apply(
            {"params": p, "batch_stats": st}, pre(xb), output="logits",
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()
        return loss, new_state["batch_stats"]

    @jax.jit
    def step(p, st, o, xb, yb):
        (l, st), g = jax.value_and_grad(loss_fn, has_aux=True)(p, st, xb, yb)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), st, o, l

    bs = min(bs, len(xtr))
    r = np.random.default_rng(0)
    per_epoch = max(1, len(xtr) // bs)
    order = None
    first = last = None
    for i in range(steps):
        if i % per_epoch == 0:
            order = r.permutation(len(xtr))
        s = (i % per_epoch) * bs
        idx = order[s:s + bs]
        params, stats, opt, l = step(params, stats, opt, xtr[idx], ytr[idx])
        first = first if first is not None else l  # resolved after the loop
        last = l
    first, last = float(first), float(last)

    folded = fold_batchnorm({"params": params, "batch_stats": stats},
                            param_dtype=jnp.bfloat16)
    # publish with the MXU-shaped s2d stem — same param layout (parity
    # tested in tests/test_models.py::test_s2d_stem_matches_direct_stem)
    net = resnet18_thin(norm="none", stem="s2d")
    bundle = ModelBundle(module=net, params=folded, input_spec=(32, 32, 3),
                         output_names=type(net).OUTPUT_NAMES,
                         preprocess="imagenet_norm",
                         name="ResNet_Small_Infer")

    jeval = jax.jit(lambda p, xb: net.apply({"params": p}, pre(xb),
                                            output="logits"))
    preds = []
    for s in range(0, len(xte), 256):
        preds.append(np.asarray(jeval(folded, xte[s:s + 256])).argmax(-1))
    acc = float((np.concatenate(preds) == yte).mean())
    print(f"  ResNet_Small_Infer: loss {first:.3f} -> {last:.3f} "
          f"({steps} steps), folded held-out accuracy {acc:.3f}")
    return bundle, acc


def _class_blobs(n, shape, n_classes, seed=0):
    """Deterministic learnable image task (kept for the full-size
    stand-ins): class-dependent mean shift."""
    r = np.random.default_rng(seed)
    y = r.integers(0, n_classes, n)
    x = r.normal(size=(n,) + shape).astype(np.float32) * 20 + 128
    shift = (y[:, None].astype(np.float32) - n_classes / 2) * 8
    x = np.clip(x + shift[..., None, None], 0, 255)
    return x.astype(np.float32), y


def build(repo_dir: str, scale: str = "small") -> list:
    from mmlspark_tpu.data.downloader import ModelSchema, publish_model
    from mmlspark_tpu.models.zoo import get_model

    published = []

    def publish(bundle, dataset, model_type, layer_count,
                eval_metric="", eval_value=0.0):
        entry = publish_model(bundle, repo_dir, ModelSchema(
            name=bundle.name, dataset=dataset, model_type=model_type,
            input_node="input", num_layers=layer_count,
            eval_metric=eval_metric, eval_value=round(eval_value, 4)))
        published.append(entry)
        ev = (f", {eval_metric}={eval_value:.3f}" if eval_metric else "")
        print(f"  published {entry.name} ({entry.size} bytes, "
              f"sha256 {entry.hash[:12]}…{ev})")

    xtr, ytr, xte, yte = digits_rgb32()

    print("ConvNet_CIFAR10 (notebook-301 flagship) — digits-rgb32")
    # small scale keeps CI fast; full scale publishes the MXU-sized widths
    conv_kw = {} if scale == "full" else {
        "widths": (16, 32), "dense_width": 64}
    b = get_model("ConvNet_CIFAR10", **conv_kw)
    b, acc = _train_eval(b, xtr, ytr, xte, yte)
    publish(b, "digits-rgb32", "CNN", 8, "accuracy", acc)

    print("ResNet_Small (CI-scale ResNet family) — digits-rgb32")
    b = get_model("ResNet_Small", num_classes=10)
    b, acc = _train_eval(b, xtr, ytr, xte, yte)
    publish(b, "digits-rgb32", "ResNet", 18, "accuracy", acc)

    print("ResNet_Small_Infer (publish-time frozen-BN fold) — digits-rgb32")
    b, acc = _train_bn_and_fold(xtr, ytr, xte, yte)
    publish(b, "digits-rgb32", "ResNet-folded", 18, "accuracy", acc)

    print("ViT_Tiny (CI-scale ViT family) — digits-rgb32")
    b = get_model("ViT_Tiny", num_classes=10)
    b, acc = _train_eval(b, xtr, ytr, xte, yte)
    publish(b, "digits-rgb32", "ViT", 2, "accuracy", acc)

    print("BiLSTM_MedTag (notebook-304 tagger) — synthetic rule")
    import jax
    import optax

    vocab, tags, L = 512, 8, 64
    r = np.random.default_rng(2)
    toks = r.integers(1, vocab, size=(320, L)).astype(np.int32)
    # learnable rule: tag = token bucket
    tag = (toks % tags).astype(np.int32)
    tr_t, te_t = toks[:256], toks[256:]
    tr_y, te_y = tag[:256], tag[256:]
    b = get_model("BiLSTM_MedTag", vocab_size=vocab, num_tags=tags,
                  max_len=L, embed_dim=32, hidden=32)
    tx = optax.adam(3e-3)
    opt = tx.init(b.params)
    params = b.params

    def tag_loss(p, xb, yb):
        lg = b.module.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, yb).mean()

    @jax.jit
    def tstep(p, o, xb, yb):
        l, g = jax.value_and_grad(tag_loss)(p, xb, yb)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), o, l

    first = last = None
    for i in range(120):
        s = (i * 64) % 192
        params, opt, l = tstep(params, opt, tr_t[s:s + 64], tr_y[s:s + 64])
        first = first if first is not None else l  # resolved after the loop
        last = l
    first, last = float(first), float(last)
    preds = np.asarray(jax.jit(
        lambda p, xb: b.module.apply({"params": p}, xb))(params, te_t)
    ).argmax(-1)
    tok_acc = float((preds == te_y).mean())
    print(f"  BiLSTM_MedTag: loss {first:.3f} -> {last:.3f}, "
          f"held-out token accuracy {tok_acc:.3f}")
    b.params = params
    publish(b, "MedEntity-synthetic", "BiLSTM", 2,
            "token_accuracy", tok_acc)

    if scale == "full":
        # full-size stand-ins: honest manifests (dataset says standin, no
        # eval claim) — real ImageNet-class pretraining needs data egress
        print("ResNet50 (full size, stand-in weights)")
        x64, y64 = _class_blobs(32, (64, 64, 3), 10, seed=3)
        b = get_model("ResNet50", num_classes=10, input_size=64)
        b, _ = _train_eval(b, x64, y64, x64, y64, steps=10, bs=32)
        publish(b, "synthetic-standin", "ResNet", 50)
        print("ResNet50_Infer (full size, folded inference variant)")
        # the featurization-serving form: frozen-BN folded + bf16 + s2d
        # stem (models/resnet.py; 0.64 MFU vs 0.39 unfolded, PERF_NOTES)
        b = get_model("ResNet50_Infer", num_classes=10, input_size=224)
        publish(b, "synthetic-standin", "ResNet-folded", 50)
        print("ViT_B16 (full size, stand-in weights)")
        x224, y224 = _class_blobs(16, (224, 224, 3), 10, seed=4)
        b = get_model("ViT_B16", num_classes=10)
        b, _ = _train_eval(b, x224, y224, x224, y224, steps=5, bs=16)
        publish(b, "synthetic-standin", "ViT", 12)

    return published


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("repo_dir")
    ap.add_argument("--scale", choices=("small", "full"), default="small")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    entries = build(args.repo_dir, args.scale)
    print(f"published {len(entries)} models to {args.repo_dir}")


if __name__ == "__main__":
    main()

"""Build a local pretrained-model repository (the zoo-publishing tool).

The reference serves pretrained CNTK models from an Azure CDN manifest
(reference: ModelDownloader.scala:184-186). This environment has no egress,
so the equivalent is a reproducible local repository: each zoo architecture
is initialized deterministically, briefly trained on a deterministic
synthetic task (so the weights are *trained*, not random — downstream
accuracy tests can assert learning happened), and published with
``publish_model`` (manifest + sha256).

Usage:
    mmlspark-tpu-build-repo <repo_dir> [--scale small|full]
    (or: python -m mmlspark_tpu.tools.build_model_repo <repo_dir>)

``small`` (default) publishes CI-scale models in seconds; ``full`` also
publishes ResNet50 / ViT_B16 at real size (minutes; weights are
few-step-trained, standing in for real pretraining which needs data egress).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def _train_briefly(bundle, x, y, steps: int = 60, lr: float = 1e-3):
    """A few deterministic Adam steps; returns the bundle with trained
    params."""
    import jax
    import jax.numpy as jnp
    import optax

    from mmlspark_tpu.models.bundle import PREPROCESSORS

    tx = optax.adam(lr)
    opt = tx.init(bundle.params)
    params = bundle.params
    # train through the same preprocessing the scoring path applies
    pre = PREPROCESSORS.get(bundle.preprocess) if bundle.preprocess else None

    def loss_fn(p, xb, yb):
        if pre is not None:
            xb = pre(xb)
        logits = bundle.module.apply({"params": p}, xb, output="logits")
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    @jax.jit
    def step(p, o, xb, yb):
        l, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), o, l

    bs = min(64, len(x))
    first = last = None
    for i in range(steps):
        s = (i * bs) % max(1, len(x) - bs + 1)
        params, opt, l = step(params, opt, x[s:s + bs], y[s:s + bs])
        if first is None:
            first = float(l)
        last = float(l)
    print(f"  {bundle.name}: loss {first:.3f} -> {last:.3f} "
          f"({steps} steps)")
    bundle.params = params
    return bundle


def _class_blobs(n, shape, n_classes, seed=0):
    """Deterministic learnable image task: class-dependent mean shift."""
    r = np.random.default_rng(seed)
    y = r.integers(0, n_classes, n)
    x = r.normal(size=(n,) + shape).astype(np.float32) * 20 + 128
    shift = (y[:, None].astype(np.float32) - n_classes / 2) * 8
    x = np.clip(x + shift[..., None, None], 0, 255)
    return x.astype(np.float32), y


def build(repo_dir: str, scale: str = "small") -> list:
    from mmlspark_tpu.data.downloader import ModelSchema, publish_model
    from mmlspark_tpu.models.zoo import get_model

    published = []

    def publish(bundle, dataset, model_type, layer_count):
        entry = publish_model(bundle, repo_dir, ModelSchema(
            name=bundle.name, dataset=dataset, model_type=model_type,
            input_node="input", num_layers=layer_count))
        published.append(entry)
        print(f"  published {entry.name} ({entry.size} bytes, "
              f"sha256 {entry.hash[:12]}…)")

    n_cls = 10
    print("ConvNet_CIFAR10 (notebook-301 flagship)")
    x, y = _class_blobs(256, (32, 32, 3), n_cls, seed=1)
    # small scale keeps CI fast; full scale publishes the MXU-sized widths
    conv_kw = {} if scale == "full" else {
        "widths": (16, 32), "dense_width": 64}
    b = get_model("ConvNet_CIFAR10", **conv_kw)
    publish(_train_briefly(b, x, y), "CIFAR10-synthetic", "CNN", 8)

    print("ResNet_Small (CI-scale ResNet family)")
    b = get_model("ResNet_Small", num_classes=n_cls)
    publish(_train_briefly(b, x, y), "CIFAR10-synthetic", "ResNet", 18)

    print("ViT_Tiny (CI-scale ViT family)")
    b = get_model("ViT_Tiny", num_classes=n_cls)
    publish(_train_briefly(b, x, y), "CIFAR10-synthetic", "ViT", 2)

    print("BiLSTM_MedTag (notebook-304 tagger)")
    import jax
    import jax.numpy as jnp
    import optax

    vocab, tags, L = 512, 8, 64
    r = np.random.default_rng(2)
    toks = r.integers(1, vocab, size=(256, L)).astype(np.int32)
    # learnable rule: tag = token bucket
    tag = (toks % tags).astype(np.int32)
    b = get_model("BiLSTM_MedTag", vocab_size=vocab, num_tags=tags,
                  max_len=L, embed_dim=32, hidden=32)
    tx = optax.adam(3e-3)
    opt = tx.init(b.params)
    params = b.params

    def tag_loss(p, xb, yb):
        lg = b.module.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, yb).mean()

    @jax.jit
    def tstep(p, o, xb, yb):
        l, g = jax.value_and_grad(tag_loss)(p, xb, yb)
        up, o = tx.update(g, o)
        return optax.apply_updates(p, up), o, l

    first = last = None
    for i in range(80):
        s = (i * 64) % 192
        params, opt, l = tstep(params, opt, toks[s:s + 64], tag[s:s + 64])
        first = first if first is not None else float(l)
        last = float(l)
    print(f"  BiLSTM_MedTag: loss {first:.3f} -> {last:.3f}")
    b.params = params
    publish(b, "MedEntity-synthetic", "BiLSTM", 2)

    if scale == "full":
        print("ResNet50 (full size, few-step-trained)")
        x224, y224 = _class_blobs(32, (64, 64, 3), n_cls, seed=3)
        b = get_model("ResNet50", num_classes=n_cls, input_size=64)
        publish(_train_briefly(b, x224, y224, steps=10), "synthetic",
                "ResNet", 50)
        print("ViT_B16 (full size, few-step-trained)")
        x224, y224 = _class_blobs(16, (224, 224, 3), n_cls, seed=4)
        b = get_model("ViT_B16", num_classes=n_cls)
        publish(_train_briefly(b, x224, y224, steps=5), "synthetic",
                "ViT", 12)

    return published


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("repo_dir")
    ap.add_argument("--scale", choices=("small", "full"), default="small")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    entries = build(args.repo_dir, args.scale)
    print(f"published {len(entries)} models to {args.repo_dir}")


if __name__ == "__main__":
    main()

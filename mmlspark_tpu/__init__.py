"""mmlspark_tpu — a TPU-native ML-pipeline framework.

A brand-new framework with the capabilities of MMLSpark (Microsoft ML for
Apache Spark, reference at /root/reference): composable columnar ML pipelines —
image/binary ingestion, image transforms, automatic featurization of
mixed-type tabular data, text featurization, one-call classifier/regressor
training, metadata-driven evaluation and model selection, a pretrained model
zoo, and deep-learning transformers for batched inference and distributed
training — designed TPU-first on JAX/XLA/Pallas/pjit rather than ported.

Where the reference runs CNTK via JNI inside Spark executors and shells out to
``mpiexec cntk`` for MPI training (reference: cntk-model/src/main/scala/
CNTKModel.scala, cntk-train/src/main/scala/CNTKLearner.scala), this framework
batches columnar partitions into padded device arrays for jit-compiled JAX
functions and trains in-process with ``shard_map``/``pjit`` using XLA
collectives over ICI/DCN.
"""

__version__ = "0.1.0"

from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core.stage import Transformer, Estimator, PipelineStage
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.analysis import TableSchema, analyze

__all__ = [
    "Param",
    "Params",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Pipeline",
    "PipelineModel",
    "DataTable",
    "TableSchema",
    "analyze",
    "__version__",
]

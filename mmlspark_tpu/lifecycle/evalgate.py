"""Eval-gated publication: which checkpoints deserve to become versions.

The train supervisor sees every worker's eval (loss) series through the
beacons and the result files; this module is the *pure* judgement over
that series — no filesystem, no repo, no clock — in the same
signal → action discipline as :class:`RecoveryPolicy` (PR 11) and
:class:`PromotionPolicy` (PR 13). The decision table
(docs/lifecycle.md):

=====================================  ==============================
series evidence                        decision
=====================================  ==============================
fewer than ``min_points`` points       reject (not enough evidence)
a non-finite value anywhere            reject (diverged / NaN'd runs
                                       never ship)
tail mean above ``max_metric``         reject (absolute quality floor)
tail did not improve on the head by    reject (training went nowhere —
``min_improvement``                    or backward)
tail worse than the best published     reject (a regression vs what
metric + ``regress_tolerance``         already shipped)
otherwise                              publish, metric = tail mean
=====================================  ==============================

Metrics are losses: **lower is better**. The ledger is the cross-run
memory (what already shipped and at what metric); the caller mutates it
on the action it takes, never the gate.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence


@dataclasses.dataclass
class EvalLedger:
    """What the gate conditions on across decisions: every
    ``(step, metric)`` it has already published — the regression
    baseline — and how many candidates it turned away."""

    published: list = dataclasses.field(default_factory=list)
    rejects: int = 0

    @property
    def best(self) -> float | None:
        """The best (lowest) metric that ever shipped, or None."""
        return min((m for _step, m in self.published), default=None)


@dataclasses.dataclass(frozen=True)
class Publish:
    """Ship it: ``metric`` is the tail mean the manifest will carry."""

    metric: float
    reason: str


@dataclasses.dataclass(frozen=True)
class Reject:
    reason: str


Decision = Any  # Publish | Reject


@dataclasses.dataclass(frozen=True)
class EvalGate:
    """Pure eval-series → publish/reject policy (see module table).

    ``tail`` is the smoothing window: the candidate's quality is the
    mean of the last ``tail`` points, judged for improvement against
    the mean of the *first* ``tail`` points of the same series."""

    min_points: int = 4
    tail: int = 4
    max_metric: float | None = None
    min_improvement: float = 0.0
    regress_tolerance: float = 0.0

    def __post_init__(self) -> None:
        if self.min_points < 1:
            raise ValueError(f"min_points must be >= 1: {self.min_points}")
        if self.tail < 1:
            raise ValueError(f"tail must be >= 1: {self.tail}")
        if self.min_improvement < 0 or self.regress_tolerance < 0:
            raise ValueError("min_improvement and regress_tolerance "
                             "must be >= 0")

    def decide(self, series: Sequence[float],
               ledger: EvalLedger) -> Decision:
        values = [float(v) for v in series]
        need = max(self.min_points, self.tail)
        if len(values) < need:
            return Reject(f"eval series has {len(values)} point(s), "
                          f"need >= {need}")
        if not all(math.isfinite(v) for v in values):
            return Reject("eval series contains non-finite values "
                          "(diverged run)")
        tail_mean = sum(values[-self.tail:]) / self.tail
        head = values[:self.tail]
        head_mean = sum(head) / len(head)
        if self.max_metric is not None and tail_mean > self.max_metric:
            return Reject(f"tail metric {tail_mean:.4g} above the "
                          f"quality floor {self.max_metric:g}")
        improved = head_mean - tail_mean
        required = self.min_improvement * abs(head_mean)
        if improved < required:
            return Reject(
                f"tail metric {tail_mean:.4g} did not improve on the "
                f"head {head_mean:.4g} by {self.min_improvement:g} "
                f"(improved {improved:.4g}, need >= {required:.4g})")
        best = ledger.best
        if best is not None and tail_mean > best + self.regress_tolerance:
            return Reject(f"tail metric {tail_mean:.4g} regresses on "
                          f"the best published {best:.4g} "
                          f"(+{self.regress_tolerance:g} tolerance)")
        return Publish(
            metric=tail_mean,
            reason=(f"tail metric {tail_mean:.4g} over {self.tail} "
                    f"point(s), improved {improved:.4g} on the head"
                    + ("" if best is None
                       else f", best published {best:.4g}")))

"""The rollout driver: ``published → shadow → canary → promoted`` per
version, over a single server or the PR 19 fleet.

The :class:`Deployer` is the serve-side half of the deployment plane —
the supervisor pattern once more: each :meth:`tick` samples its target
into one typed :class:`~mmlspark_tpu.lifecycle.rollout.RolloutSignal`,
the pure :class:`~mmlspark_tpu.lifecycle.rollout.RolloutPolicy`
decides, and the deployer actuates:

* :class:`ServerTarget` drives one in-process
  :class:`~mmlspark_tpu.serve.server.ModelServer` through the PR 13
  machinery — ``deploy_canary`` per stage (the server's own burn
  engine stays armed as a safety net, but promotion is the
  *deployer's* decision, via the new ``ModelServer.promote``).
* :class:`FleetTarget` fans out over a PR 19 serve fleet by writing a
  ``deploy.json`` command file the backend workers watch: each backend
  hot-swaps the version from the shared
  :class:`~mmlspark_tpu.models.repo.ModelRepo` and reports its served
  ``(model, version)`` map in its beacon — promotion blocks until
  every backend has converged (a lagging backend holds the rollout).

Parity drift or fast-burn at any stage auto-rolls back **repo-side**
(``ModelRepo.set_current`` back to the prior version) *and*
serve-side, journaled. Every transition lands in
``<dir>/decisions.jsonl`` (shared ``service/core.py`` journal
machinery) cross-referencing the train and serve supervisors' own
journals; obs mirrors them as ``lifecycle/*`` events with
``lifecycle.rollouts``/``lifecycle.rollbacks`` counters and the
``deploy.wall_s`` gauge stamped on promotion.
:func:`replay_decisions` reconstructs every rollout's trajectory from
the journal alone — the forensic contract the tests pin.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
from typing import Any

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.lifecycle.publish import lifecycle_journal
from mmlspark_tpu.lifecycle.rollout import (
    Abort, Advance, Hold, RolloutLedger, RolloutPolicy, RolloutSignal,
)
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.serve.lifecycle import CanarySignal

_log = get_logger(__name__)

DEPLOY_FILE = "deploy.json"

_BEACON_RE = re.compile(r"^beacon_(\d+)\.json$")


@dataclasses.dataclass
class Rollout:
    """One version's journey through the deployer (mutable state the
    ticks advance). ``version`` is None until a publish-stage rollout
    has actually published its bundle."""

    model: str
    version: int | None = None
    bundle: Any = None
    provenance: dict | None = None
    prior_version: int | None = None
    ledger: RolloutLedger = dataclasses.field(
        default_factory=RolloutLedger)
    started: float = dataclasses.field(default_factory=time.monotonic)
    outcome: str | None = None  # "promoted" | "rolled_back"

    @property
    def done(self) -> bool:
        return self.outcome is not None


class ServerTarget:
    """Drive one in-process :class:`ModelServer` (PR 13 canary
    machinery). ``wrap`` optionally maps the repo artifact to the
    served transformer (default: serve it as loaded — a raw
    ``ModelBundle`` becomes a ``JaxModel`` on columns
    ``input``/``scores``, the server's own convention)."""

    def __init__(self, server: Any, model: str, wrap: Any = None,
                 schema: Any = None, example: Any = None):
        self.server = server
        self.model = model
        self.wrap = wrap
        self.schema = schema
        self.example = example
        self._artifacts: dict[int, Any] = {}
        self._tolerance: float | None = None

    def _materialize(self, repo: Any, version: int) -> Any:
        if version not in self._artifacts:
            model, _info = repo.load(self.model, version)
            if self.wrap is not None:
                model = self.wrap(model)
            self._artifacts[version] = model
        return self._artifacts[version]

    def begin(self, repo: Any, rollout: Rollout, stage: str,
              fraction: float, tolerance: float | None,
              fast_burn: float) -> None:
        from mmlspark_tpu.serve.lifecycle import PromotionPolicy
        self._tolerance = tolerance if stage == "shadow" else None
        # the server's own burn engine stays armed (fast rollback even
        # between deployer ticks) but may never promote: promotion is
        # the deployer's decision, gated on policy + convergence
        self.server.deploy_canary(
            self.model, self._materialize(repo, rollout.version),
            mode=stage, fraction=fraction, version=rollout.version,
            schema=self.schema, example=self.example,
            policy=PromotionPolicy(fast_burn=fast_burn,
                                   promote_after=10 ** 9),
            parity_tolerance=self._tolerance)

    def observe(self, rollout: Rollout, stage: str) -> dict:
        if stage == "promoting":
            snap = self.server.snapshot().get(self.model) or {}
            converged = snap.get("version") == rollout.version
            return {"serve": None, "action": None,
                    "converged": converged,
                    "lagging": () if converged else (self.model,),
                    "healthy": True}
        detail = self.server.lifecycle_tick(self.model)
        if detail is None:
            # no canary attached: either the server's own burn engine
            # already rolled it back (honor that) or a racing close
            for rec in self.server.lifecycle_decisions("rollback"):
                if rec.get("version") == rollout.version:
                    return {"serve": None, "action": "rollback",
                            "converged": False, "lagging": (),
                            "healthy": False}
            return {"serve": None, "action": None, "converged": False,
                    "lagging": (), "healthy": False}
        serve = CanarySignal(
            burn_short=detail.get("burn_short"),
            burn_long=detail.get("burn_long"),
            terminal_window=int(detail.get("terminal_window") or 0),
            parity_drift=detail.get("parity_drift"),
            parity_tolerance=self._tolerance)
        return {"serve": serve, "action": detail.get("action"),
                "converged": True, "lagging": (), "healthy": True}

    def promote(self, rollout: Rollout) -> None:
        self.server.promote(self.model, reason="deployer promotion")

    def rollback(self, rollout: Rollout, reason: str) -> None:
        self.server.rollback(self.model, reason=reason)


class FleetTarget:
    """Fan a rollout out over a PR 19 serve fleet.

    Actuation is a ``deploy.json`` command file in the fleet service
    dir (``{"seq", "model", "version", "repo", "backends"}``) that the
    backend workers watch: each in-scope backend hot-swaps the version
    from the shared repo (``ModelServer.add_model_from_repo`` — digest
    verify first, zero-drop flip) and reports its served
    ``(model, version)`` map in its beacon. On a fleet, both ramp
    stages are subset rollouts (``canary_backends`` backends first;
    cross-process shadow mirroring does not exist), and promotion
    re-targets ``"all"`` — convergence is read back off the beacons,
    so a lagging backend blocks promotion visibly."""

    def __init__(self, service_dir: str, repo_root: str,
                 canary_backends: int = 1):
        self.service_dir = service_dir
        self.repo_root = repo_root
        self.canary_backends = max(1, int(canary_backends))
        self._scope: Any = ()
        self._seq = self._load_seq()

    def _load_seq(self) -> int:
        try:
            with open(os.path.join(self.service_dir, DEPLOY_FILE),
                      encoding="utf-8") as f:
                return int(json.load(f).get("seq", 0))
        except (OSError, ValueError):
            return 0

    def _command(self, model: str, version: int,
                 backends: Any) -> None:
        from mmlspark_tpu.service.core import atomic_write_json
        self._seq += 1
        atomic_write_json(
            os.path.join(self.service_dir, DEPLOY_FILE),
            {"seq": self._seq, "model": model, "version": version,
             "repo": self.repo_root, "backends": backends})

    def _beacons(self) -> dict[int, dict]:
        out: dict[int, dict] = {}
        try:
            names = os.listdir(self.service_dir)
        except OSError:
            return out
        for fname in names:
            m = _BEACON_RE.match(fname)
            if not m:
                continue
            try:
                with open(os.path.join(self.service_dir, fname),
                          encoding="utf-8") as f:
                    out[int(m.group(1))] = json.load(f)
            except (OSError, ValueError):
                continue
        return out

    def _running(self) -> dict[int, dict]:
        return {bid: b for bid, b in self._beacons().items()
                if b.get("status") == "running"}

    def begin(self, repo: Any, rollout: Rollout, stage: str,
              fraction: float, tolerance: float | None,
              fast_burn: float) -> None:
        running = sorted(self._running())
        self._scope = tuple(running[:self.canary_backends])
        self._command(rollout.model, rollout.version,
                      list(self._scope))

    def observe(self, rollout: Rollout, stage: str) -> dict:
        running = self._running()
        scope = (sorted(running) if self._scope == "all"
                 else list(self._scope))
        lagging = tuple(
            bid for bid in scope
            if (running.get(bid) or {}).get("versions", {})
            .get(rollout.model) != rollout.version)
        healthy = bool(scope) and all(bid in running for bid in scope)
        burns = [float(running[bid].get("burn_short", 0.0))
                 for bid in scope if bid in running]
        serve = None
        if healthy and not lagging and stage != "promoting":
            serve = CanarySignal(burn_short=max(burns, default=0.0))
        return {"serve": serve, "action": None,
                "converged": healthy and not lagging,
                "lagging": lagging, "healthy": healthy}

    def promote(self, rollout: Rollout) -> None:
        self._scope = "all"
        self._command(rollout.model, rollout.version, "all")

    def rollback(self, rollout: Rollout, reason: str) -> None:
        if rollout.prior_version is not None:
            self._scope = "all"
            self._command(rollout.model, rollout.prior_version, "all")


class Deployer:
    """Supervise rollouts end to end (see module docstring).

    ``refs`` carries the cross-journal pointers (e.g.
    ``{"train_journal": ..., "serve_journal": ...}``) stamped into the
    ``rollout`` record so one journey reads across all three
    journals."""

    def __init__(self, directory: str, repo: Any, target: Any,
                 policy: RolloutPolicy | None = None,
                 refs: dict | None = None, run_id: str | None = None):
        from mmlspark_tpu.models.repo import ModelRepo
        self.directory = directory
        self.repo = (ModelRepo(repo) if isinstance(repo, str) else repo)
        self.target = target
        self.policy = policy or RolloutPolicy()
        self.refs = dict(refs or {})
        self.run_id = run_id or f"deploy-{os.getpid()}"
        self.journal = lifecycle_journal(directory)

    # -- rollout admission --

    def start_rollout(self, model: str, version: int | None = None,
                      bundle: Any = None,
                      provenance: dict | None = None) -> Rollout:
        """Admit one rollout: either a published ``version`` (from the
        train-side Publisher) or a ``bundle`` the deployer publishes
        itself on its first tick (so a torn publish is retried by the
        next tick, never dropped)."""
        if (version is None) == (bundle is None):
            raise ValueError(
                "start_rollout needs exactly one of version= (already "
                "published) or bundle= (publish on first tick)")
        versions = self.repo.versions(model)
        prior = self.repo.current_version(model) if versions else None
        rollout = Rollout(model=model, version=version, bundle=bundle,
                          provenance=provenance, prior_version=prior)
        if version is not None:
            self.repo.verify(model, version)
            rollout.ledger.stage = "published"
        self.journal.record("rollout", {
            "model": model, "version": version,
            "prior_version": prior, "run_id": self.run_id,
            "stages": list(self.policy.stages), **self.refs})
        return rollout

    # -- one tick --

    def tick(self, rollout: Rollout) -> dict:
        """Advance ``rollout`` by at most one transition; returns what
        happened (mirrors the journal record)."""
        if rollout.done:
            return {"stage": rollout.ledger.stage, "action": "done"}
        ledger = rollout.ledger
        ledger.ticks += 1
        ledger.stage_ticks += 1
        if ledger.stage == "publish":
            return self._tick_publish(rollout)
        if ledger.stage == "published":
            return self._enter_next_stage(rollout)
        sig_bits = self.target.observe(rollout, ledger.stage)
        sig = RolloutSignal(stage=ledger.stage, **sig_bits)
        action = self.policy.decide(sig, ledger)
        if isinstance(action, Abort):
            return self._rollback(rollout, action.reason)
        if isinstance(action, Advance):
            if ledger.stage == "promoting":
                return self._promote(rollout, action.reason)
            return self._enter_next_stage(rollout)
        ledger.clean_ticks = (ledger.clean_ticks + 1 if action.clean
                              else 0)
        detail = {"model": rollout.model, "version": rollout.version,
                  "stage": ledger.stage, "reason": action.reason,
                  "clean_ticks": ledger.clean_ticks,
                  "ticks": ledger.ticks}
        serve = sig.serve
        if serve is not None:
            detail["burn_short"] = serve.burn_short
            detail["parity_drift"] = serve.parity_drift
        if sig.lagging:
            detail["lagging"] = list(sig.lagging)
        self.journal.record("hold", detail)
        return {"action": "hold", **detail}

    def _tick_publish(self, rollout: Rollout) -> dict:
        try:
            version = self.repo.publish(
                rollout.model, rollout.bundle,
                provenance=rollout.provenance, set_current=False)
        except Exception as e:
            # staging discipline: nothing partial became visible and
            # CURRENT never moved — hold the stage, next tick retries
            detail = {"model": rollout.model,
                      "stage": "publish",
                      "error": f"{type(e).__name__}: {e}"}
            self.journal.record("publish_torn", detail)
            return {"action": "publish_torn", **detail}
        rollout.version = version
        self._set_stage(rollout, "published")
        detail = {"model": rollout.model, "version": version,
                  "prior_version": rollout.prior_version, "dark": True}
        self.journal.record("publish", detail)
        return {"action": "publish", **detail}

    # -- transitions --

    def _set_stage(self, rollout: Rollout, stage: str) -> None:
        rollout.ledger.stage = stage
        rollout.ledger.stage_ticks = 0
        rollout.ledger.clean_ticks = 0

    def _enter_next_stage(self, rollout: Rollout) -> dict:
        stages = list(self.policy.stages)
        current = rollout.ledger.stage
        if current in stages and stages.index(current) + 1 < len(stages):
            nxt = stages[stages.index(current) + 1]
        elif current == "published" and stages:
            nxt = stages[0]
        else:
            nxt = "promoting"
        detail: dict = {"model": rollout.model,
                        "version": rollout.version, "stage": nxt}
        if nxt == "promoting":
            # serve-side flip first; repo CURRENT flips only once the
            # target reports every backend converged
            self.target.promote(rollout)
        else:
            fraction = self.policy.fraction(nxt)
            detail["fraction"] = fraction
            self.target.begin(self.repo, rollout, nxt, fraction,
                              self.policy.parity_tolerance,
                              self.policy.fast_burn)
        self._set_stage(rollout, nxt)
        self.journal.record("stage", detail)
        return {"action": "stage", **detail}

    def _promote(self, rollout: Rollout, reason: str) -> dict:
        self.repo.set_current(rollout.model, rollout.version)
        wall = round(time.monotonic() - rollout.started, 6)
        self._set_stage(rollout, "promoted")
        rollout.outcome = "promoted"
        if _obs_rt._enabled:
            _obs_registry().gauge("deploy.wall_s",
                                  model=rollout.model).set(wall)
        detail = {"model": rollout.model, "version": rollout.version,
                  "prior_version": rollout.prior_version,
                  "reason": reason, "wall_s": wall,
                  "ticks": rollout.ledger.ticks}
        self.journal.record("promote", detail)
        return {"action": "promote", **detail}

    def _rollback(self, rollout: Rollout, reason: str) -> dict:
        stage = rollout.ledger.stage
        try:
            self.target.rollback(rollout, reason)
        except Exception as e:  # pragma: no cover - serve side already
            _log.warning("lifecycle: serve-side rollback failed: %s", e)
        if rollout.prior_version is not None:
            # repo-side rollback: CURRENT pinned back to the prior
            # version (idempotent when it never moved)
            self.repo.set_current(rollout.model, rollout.prior_version)
        self._set_stage(rollout, "rolled_back")
        rollout.outcome = "rolled_back"
        detail = {"model": rollout.model, "version": rollout.version,
                  "prior_version": rollout.prior_version,
                  "stage": stage, "reason": reason,
                  "ticks": rollout.ledger.ticks}
        self.journal.record("rollback", detail)
        return {"action": "rollback", **detail}

    # -- the driver loop --

    def run(self, rollout: Rollout, tick_s: float = 0.25,
            timeout_s: float = 120.0) -> str:
        """Tick until the rollout terminates; a rollout that cannot
        terminate inside ``timeout_s`` is rolled back (a deploy that
        hangs is a failed deploy). Returns the outcome."""
        deadline = time.monotonic() + timeout_s
        while not rollout.done:
            self.tick(rollout)
            if rollout.done:
                break
            if time.monotonic() > deadline:
                self._rollback(rollout, f"deploy timed out after "
                                        f"{timeout_s:g}s in stage "
                                        f"{rollout.ledger.stage!r}")
                break
            time.sleep(tick_s)
        return rollout.outcome or rollout.ledger.stage


def replay_decisions(path: str) -> list[dict]:
    """Reconstruct every rollout's trajectory from ``decisions.jsonl``
    alone: one dict per ``rollout`` record with the stages it entered,
    the version it (eventually) carried, and its terminal outcome.
    The forensic contract: a live :class:`Rollout`'s journey and the
    replay of its journal must agree."""
    rollouts: list[dict] = []
    open_by_model: dict[str, dict] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.get("kind")
            model = rec.get("model")
            if kind == "rollout":
                entry = {"model": model, "version": rec.get("version"),
                         "prior_version": rec.get("prior_version"),
                         "stages": [], "outcome": None, "reason": None}
                rollouts.append(entry)
                open_by_model[model] = entry
                continue
            entry = open_by_model.get(model)
            if entry is None or entry["outcome"] is not None:
                continue
            if kind == "publish" and entry["version"] is None:
                entry["version"] = rec.get("version")
            elif kind == "stage":
                entry["stages"].append(rec.get("stage"))
            elif kind == "promote":
                entry["outcome"] = "promoted"
                entry["reason"] = rec.get("reason")
            elif kind == "rollback":
                entry["outcome"] = "rolled_back"
                entry["reason"] = rec.get("reason")
    return rollouts

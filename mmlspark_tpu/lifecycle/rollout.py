"""Pure rollout policy: one version's ``published → shadow → canary →
promoted`` progression as signal → action decisions.

Same discipline as :class:`~mmlspark_tpu.train.service.RecoveryPolicy`
(PR 11) and :class:`~mmlspark_tpu.serve.lifecycle.PromotionPolicy`
(PR 13): the :class:`Deployer` samples its target (a single
``ModelServer`` or the PR 19 fleet) into one typed
:class:`RolloutSignal` per tick, the frozen :class:`RolloutPolicy`
decides, and the deployer actuates — ledger mutation happens at the
call site, never in the policy. The decision table
(docs/lifecycle.md):

==========================================  ========================
signal                                      action
==========================================  ========================
serve side already rolled the canary back   abort (the burn engine
(``action == "rollback"``)                  fired first — honor it)
parity drift above tolerance                abort
short-window burn ≥ ``fast_burn``           abort
stage tick budget exhausted                 abort (a rollout that
                                            cannot converge is a
                                            failed rollout)
unhealthy / no verdict                      hold, streak reset
clean tick                                  bank it; ``advance_after``
                                            consecutive clean ticks
                                            advance the stage
promoting stage, a backend still on the     hold (promotion blocks on
old version                                 fleet convergence)
promoting stage, every backend converged    advance → promoted
==========================================  ========================
"""

from __future__ import annotations

import dataclasses
from typing import Any

from mmlspark_tpu.serve.lifecycle import CanarySignal


@dataclasses.dataclass(frozen=True)
class RolloutSignal:
    """One deployer tick's sensor reading: which stage the rollout is
    in, the serve plane's canary sensors (None before any deploy), the
    serve side's own lifecycle verdict this tick (``"hold"`` /
    ``"rollback"`` / ``"promote"`` / None), and — for fleet targets —
    whether every in-scope backend serves the target version yet."""

    stage: str
    serve: CanarySignal | None = None
    action: str | None = None
    converged: bool = True
    lagging: tuple = ()
    healthy: bool = True


@dataclasses.dataclass
class RolloutLedger:
    """What the policy conditions on across ticks (mutated by the
    deployer, never the policy)."""

    stage: str = "publish"
    ticks: int = 0
    stage_ticks: int = 0
    clean_ticks: int = 0


@dataclasses.dataclass(frozen=True)
class Advance:
    reason: str


@dataclasses.dataclass(frozen=True)
class Hold:
    reason: str = ""
    clean: bool = False  # this tick banks toward advance_after


@dataclasses.dataclass(frozen=True)
class Abort:
    reason: str


Action = Any  # Advance | Hold | Abort


@dataclasses.dataclass(frozen=True)
class RolloutPolicy:
    """Signal → action, pure (see module table). ``stages`` is the
    traffic ramp between ``published`` and ``promoting``; fractions map
    each stage to its mirror/split share of stable traffic."""

    stages: tuple = ("shadow", "canary")
    advance_after: int = 2
    fast_burn: float = 14.0
    parity_tolerance: float | None = None
    shadow_fraction: float = 1.0
    canary_fraction: float = 0.5
    max_stage_ticks: int = 240

    def __post_init__(self) -> None:
        if self.advance_after < 1:
            raise ValueError(
                f"advance_after must be >= 1: {self.advance_after}")
        if self.fast_burn <= 0:
            raise ValueError(f"fast_burn must be > 0: {self.fast_burn}")
        if self.max_stage_ticks < 1:
            raise ValueError(
                f"max_stage_ticks must be >= 1: {self.max_stage_ticks}")
        for stage in self.stages:
            if stage not in ("shadow", "canary"):
                raise ValueError(f"unknown rollout stage {stage!r} "
                                 "(stages are 'shadow' and 'canary')")

    def fraction(self, stage: str) -> float:
        return (self.shadow_fraction if stage == "shadow"
                else self.canary_fraction)

    def decide(self, sig: RolloutSignal, ledger: RolloutLedger) -> Action:
        if sig.action == "rollback":
            return Abort("serve-side lifecycle rolled the candidate "
                         "back (burn/parity verdict)")
        serve = sig.serve
        if serve is not None:
            if (serve.parity_drift is not None
                    and serve.parity_tolerance is not None
                    and serve.parity_drift > serve.parity_tolerance):
                return Abort(
                    f"parity drift {serve.parity_drift:.4g} exceeds "
                    f"tolerance {serve.parity_tolerance:g} in "
                    f"{sig.stage}")
            if (serve.burn_short is not None
                    and serve.burn_short >= self.fast_burn):
                return Abort(
                    f"fast-burn {serve.burn_short:.1f}x >= "
                    f"{self.fast_burn:g}x in {sig.stage} "
                    f"({serve.terminal_window} terminal)")
        if ledger.stage_ticks >= self.max_stage_ticks:
            return Abort(f"stage {sig.stage!r} exhausted its "
                         f"{self.max_stage_ticks}-tick budget without "
                         "converging")
        if not sig.healthy:
            return Hold(f"{sig.stage}: target unhealthy, streak reset")
        if sig.stage == "promoting":
            if not sig.converged:
                lag = ",".join(str(b) for b in sig.lagging) or "?"
                return Hold(f"promotion blocked: backend(s) {lag} "
                            "still on the old version")
            return Advance("every backend serves the target version")
        if serve is None or (serve.burn_short is None
                             and serve.parity_drift is None):
            # mirrors PR 13's "no traffic ≠ healthy": a tick with no
            # canary evidence neither banks nor advances
            return Hold(f"{sig.stage}: no canary evidence yet, "
                        "streak reset")
        if ledger.clean_ticks + 1 >= self.advance_after:
            return Advance(
                f"{ledger.clean_ticks + 1} consecutive clean tick(s) "
                f"in {sig.stage}")
        return Hold(f"clean tick {ledger.clean_ticks + 1}/"
                    f"{self.advance_after} in {sig.stage}", clean=True)

"""The train→serve deployment plane (ROADMAP item 3).

The reference's whole point was that ``CNTKLearner`` output flowed
straight into ``CNTKModel`` serving inside one pipeline; this package
closes the same loop for the reproduction: a supervised fine-tune run
*ends with the new version serving traffic*, and a degraded run ends
rolled back — the whole journey journaled and visible as one fleet
timeline.

Three layers, each in the repo's sensors → pure policy → actuator
discipline (PR 11/13/19):

* :mod:`mmlspark_tpu.lifecycle.evalgate` — which checkpoints deserve
  to ship: a pure :class:`EvalGate` judges the worker's eval (loss)
  series against an :class:`EvalLedger` of what already shipped.
* :mod:`mmlspark_tpu.lifecycle.publish` — the train-side half: the
  :class:`Publisher` the :class:`~mmlspark_tpu.train.service.TrainSupervisor`
  drives on clean generation completion (and optionally every K
  checkpoints), dark-publishing passing checkpoints to the
  :class:`~mmlspark_tpu.models.repo.ModelRepo` with provenance stamped
  in the manifest.
* :mod:`mmlspark_tpu.lifecycle.rollout` /
  :mod:`mmlspark_tpu.lifecycle.deployer` — the serve-side half: a
  :class:`Deployer` supervises ``published → shadow → canary →
  promoted`` per version over a single :class:`ModelServer` or the
  PR 19 fleet, with the pure :class:`RolloutPolicy` deciding every
  transition and parity drift / fast-burn at any stage auto-rolling
  back repo-side AND serve-side.

Every decision lands in ``<dir>/decisions.jsonl`` (the shared
``service/core.py`` journal machinery) cross-referencing the train and
serve supervisors' journals, plus obs ``lifecycle/*`` events,
``lifecycle.rollouts``/``lifecycle.rollbacks`` counters, and the
``deploy.wall_s`` gauge. See docs/lifecycle.md.
"""

from mmlspark_tpu.lifecycle.deployer import (  # noqa: F401
    Deployer, FleetTarget, Rollout, ServerTarget, replay_decisions,
)
from mmlspark_tpu.lifecycle.evalgate import (  # noqa: F401
    EvalGate, EvalLedger, Publish, Reject,
)
from mmlspark_tpu.lifecycle.publish import (  # noqa: F401
    PUBLISH_FENCE_SPAN, Publisher, PublishPolicy, bundle_from_npz,
    lifecycle_journal,
)
from mmlspark_tpu.lifecycle.rollout import (  # noqa: F401
    Abort, Advance, Hold, RolloutLedger, RolloutPolicy, RolloutSignal,
)

__all__ = [
    "Abort", "Advance", "Deployer", "EvalGate", "EvalLedger",
    "FleetTarget", "Hold", "PUBLISH_FENCE_SPAN", "Publish", "Publisher",
    "PublishPolicy", "Reject", "Rollout", "RolloutLedger",
    "RolloutPolicy", "RolloutSignal", "ServerTarget", "bundle_from_npz",
    "lifecycle_journal", "replay_decisions",
]

"""The train-side half of the deployment plane: eval-gated publication.

The :class:`Publisher` is the runtime the
:class:`~mmlspark_tpu.train.service.TrainSupervisor` owns when its
:class:`~mmlspark_tpu.train.service.ServiceConfig` carries a
:class:`PublishPolicy`:

* **on clean generation completion** the worker's result file (loss
  history + final params) is judged by the pure
  :class:`~mmlspark_tpu.lifecycle.evalgate.EvalGate`; a passing
  checkpoint is converted to a ``ModelBundle`` (the policy's
  ``bundle_from_result`` builder) and **dark-published** to the
  :class:`~mmlspark_tpu.models.repo.ModelRepo` — the atomic publish +
  digest verify already exist there — with provenance (source
  checkpoint step, eval excerpt, publisher run/generation id) stamped
  in the VERSION.json manifest. ``CURRENT`` does not move: flipping the
  pointer is the :class:`~mmlspark_tpu.lifecycle.deployer.Deployer`'s
  decision, on promotion.
* **optionally every K checkpoints** (``every_k_checkpoints``) the
  supervisor's sensor poll feeds the beacon eval series through the
  same gate mid-run; publication then needs the policy's
  ``bundle_from_checkpoint`` builder (an Orbax restore needs the
  caller's target pytree — the supervisor cannot invent one).

Every decision is journaled through :func:`lifecycle_journal` — the
shared ``service/core.py`` journal discipline: ``decisions.jsonl`` on
disk always, obs ``lifecycle/*`` events and ``lifecycle.rollouts`` /
``lifecycle.rollbacks`` counters when the tracer is on.

The publish itself is wrapped in the :data:`PUBLISH_FENCE_SPAN` obs
span — the train→deployment-plane handoff fence. The worker emits the
same span around its result write (``MMLSPARK_TPU_SERVICE_PUBLISH_FENCE``
set by the supervisor when a publish policy is configured), so the two
processes' fleet exports stitch into one Perfetto flow at exactly the
moment the checkpoint changed hands (obs/fleet.py
``FENCE_SPAN_NAMES``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Any, Callable

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.lifecycle.evalgate import (
    EvalGate, EvalLedger, Publish, Reject,
)
from mmlspark_tpu.service.core import SupervisorJournal

_log = get_logger(__name__)

# the train→deployment-plane handoff fence (obs/fleet.py stitches
# cross-process flows at this span name)
PUBLISH_FENCE_SPAN = "lifecycle/publish_fence"

# how many trailing eval points the manifest provenance carries
EVAL_EXCERPT = 6


def lifecycle_journal(directory: str) -> SupervisorJournal:
    """The deployment plane's decision journal: one ``decisions.jsonl``
    under ``directory`` (created), obs ``lifecycle/*`` events plus the
    ``lifecycle.rollouts``/``lifecycle.rollbacks`` counters when the
    tracer is enabled — the shared SupervisorJournal discipline
    (service/core.py). The Publisher and the Deployer both write
    through this, so pointing them at the same directory yields the
    single cross-referenced journey the fleet timeline stitches."""
    os.makedirs(directory, exist_ok=True)
    return SupervisorJournal(
        os.path.join(directory, "decisions.jsonl"),
        event_prefix="lifecycle", cat="lifecycle",
        counter_prefix="lifecycle.",
        counter_kinds=("rollout", "rollback"),
        log_label="lifecycle")


def _fence_span():
    """The publish-fence span when the tracer is on, else a no-op."""
    from mmlspark_tpu import obs
    if obs.enabled():
        return obs.span(PUBLISH_FENCE_SPAN, "lifecycle")
    return contextlib.nullcontext()


def bundle_from_npz(result: dict, module: Any, input_spec: tuple,
                    output_names: tuple = ("logits",)) -> Any:
    """Rebuild a ``ModelBundle`` from a worker result file's params
    export (``params_npz``: flat arrays keyed by ``/``-joined tree
    paths, exactly what ``run_selftest_worker`` writes). The caller
    supplies the module + IO contract — params files carry weights,
    not architecture."""
    import numpy as np

    from mmlspark_tpu.models.bundle import ModelBundle

    params: dict = {}
    with np.load(result["params_npz"]) as npz:
        for key in npz.files:
            node = params
            *parents, leaf = key.split("/")
            for part in parents:
                node = node.setdefault(part, {})
            node[leaf] = np.asarray(npz[key])
    return ModelBundle(module=module, params=params,
                       input_spec=tuple(input_spec),
                       output_names=tuple(output_names))


@dataclasses.dataclass
class PublishPolicy:
    """What the supervisor publishes, where, and under which gate
    (``ServiceConfig.publish``). ``bundle_from_result`` maps a worker
    result dict to a publishable ``ModelBundle``;
    ``bundle_from_checkpoint(checkpoint_dir, step)`` is the optional
    mid-run builder for the every-K path. ``set_current=False`` (the
    default) publishes dark — promotion flips ``CURRENT``."""

    model: str
    repo_root: str
    gate: EvalGate = dataclasses.field(default_factory=EvalGate)
    bundle_from_result: Callable[[dict], Any] | None = None
    every_k_checkpoints: int | None = None
    bundle_from_checkpoint: Callable[[str, int], Any] | None = None
    set_current: bool = False
    notes: str = ""
    lifecycle_dir: str | None = None  # default: <service_dir>/lifecycle

    def __post_init__(self) -> None:
        if not self.model:
            raise ValueError("publish policy needs a model name")
        if self.every_k_checkpoints is not None \
                and self.every_k_checkpoints < 1:
            raise ValueError("every_k_checkpoints must be >= 1: "
                             f"{self.every_k_checkpoints}")


class Publisher:
    """The supervisor-owned actuator over one :class:`PublishPolicy`:
    holds the repo, the cross-decision :class:`EvalLedger`, and the
    lifecycle journal. A publish that tears (the ``repo_torn_publish``
    fault class) is journaled and kept pending — the repo's staging
    discipline guarantees nothing partial became visible, so the next
    :meth:`retry_pending` re-attempts cleanly."""

    def __init__(self, policy: PublishPolicy, service_dir: str, *,
                 run_id: str, train_journal: str | None = None):
        from mmlspark_tpu.models.repo import ModelRepo
        self.policy = policy
        self.run_id = run_id
        self.train_journal = train_journal
        self.repo = ModelRepo(policy.repo_root)
        self.ledger = EvalLedger()
        self.directory = policy.lifecycle_dir or os.path.join(
            service_dir, "lifecycle")
        self.journal = lifecycle_journal(self.directory)
        self.published: list[dict] = []
        self._pending: tuple | None = None
        self._gated_steps: set[int] = set()  # every-K bookkeeping

    # -- completion-time publication --

    def on_complete(self, generation: int, result: dict) -> dict | None:
        """Judge a clean generation's eval series and publish the
        result-file params on a pass. Returns the publication record
        (also journaled) or None."""
        with _fence_span():
            series = [float(v) for v in (result.get("history") or ())]
            step = int(result.get("steps", 0))
            decision = self.policy.gate.decide(series, self.ledger)
            if isinstance(decision, Reject):
                return self._reject(generation, step, decision)
            if self.policy.bundle_from_result is None:
                self.journal.record("publish_skip", {
                    "model": self.policy.model, "generation": generation,
                    "step": step, "run_id": self.run_id,
                    "reason": "no bundle_from_result builder"})
                return None
            bundle = self.policy.bundle_from_result(result)
            return self._publish(bundle, generation, step, series,
                                 decision)

    # -- mid-run (every K checkpoints) publication --

    def on_checkpoint_poll(self, generation: int,
                           checkpoint_dir: str | None,
                           series: list) -> dict | None:
        """The supervisor's sensor-poll hook: when ``every_k_checkpoints``
        is set and K new checkpoints have landed since the last
        judgement, gate the beacon eval series; publish only when the
        policy has a checkpoint builder."""
        k = self.policy.every_k_checkpoints
        if not k or not checkpoint_dir:
            return None
        from mmlspark_tpu.train.checkpoint import TrainCheckpointer
        try:
            steps = TrainCheckpointer(checkpoint_dir).steps()
        except Exception:  # pragma: no cover - mid-write manifest
            return None
        new = [s for s in steps if s not in self._gated_steps]
        if len(new) < k:
            return None
        step = new[-1]
        self._gated_steps.update(new)
        with _fence_span():
            values = [float(v) for v in (series or ())]
            decision = self.policy.gate.decide(values, self.ledger)
            if isinstance(decision, Reject):
                return self._reject(generation, step, decision,
                                    mid_run=True)
            if self.policy.bundle_from_checkpoint is None:
                self.journal.record("publish_skip", {
                    "model": self.policy.model, "generation": generation,
                    "step": step, "run_id": self.run_id, "mid_run": True,
                    "reason": "no bundle_from_checkpoint builder"})
                return None
            bundle = self.policy.bundle_from_checkpoint(checkpoint_dir,
                                                        step)
            return self._publish(bundle, generation, step, values,
                                 decision, mid_run=True)

    # -- the actuator --

    def _reject(self, generation: int, step: int, decision: Reject,
                mid_run: bool = False) -> None:
        self.ledger.rejects += 1
        payload = {"model": self.policy.model, "generation": generation,
                   "step": step, "reason": decision.reason,
                   "run_id": self.run_id}
        if mid_run:
            payload["mid_run"] = True
        if self.train_journal:
            payload["train_journal"] = self.train_journal
        self.journal.record("publish_reject", payload)
        return None

    def _publish(self, bundle: Any, generation: int, step: int,
                 series: list, decision: Publish,
                 mid_run: bool = False) -> dict | None:
        provenance = {
            "checkpoint_step": step,
            "eval": {"metric": decision.metric,
                     "series_tail": [round(float(v), 6) for v in
                                     series[-EVAL_EXCERPT:]],
                     "points": len(series)},
            "run_id": self.run_id,
            "generation": generation,
        }
        if self.train_journal:
            provenance["train_journal"] = self.train_journal
        try:
            version = self.repo.publish(
                self.policy.model, bundle, notes=self.policy.notes,
                provenance=provenance,
                set_current=self.policy.set_current)
        except Exception as e:
            # the repo's staging discipline means nothing partial became
            # visible — keep the candidate and let the next poll retry
            self.journal.record("publish_torn", {
                "model": self.policy.model, "generation": generation,
                "step": step, "run_id": self.run_id,
                "error": f"{type(e).__name__}: {e}"})
            self._pending = (bundle, generation, step, series, decision,
                             mid_run)
            return None
        self._pending = None
        self.ledger.published.append((step, decision.metric))
        record = {
            "model": self.policy.model, "version": version,
            "generation": generation, "step": step,
            "metric": round(float(decision.metric), 6),
            "dark": not self.policy.set_current,
            "run_id": self.run_id, "reason": decision.reason,
        }
        if mid_run:
            record["mid_run"] = True
        if self.train_journal:
            record["train_journal"] = self.train_journal
        self.published.append(record)
        self.journal.record("publish", record)
        return record

    def retry_pending(self) -> dict | None:
        """Re-attempt a torn publish (None when nothing is pending)."""
        if self._pending is None:
            return None
        bundle, generation, step, series, decision, mid_run = \
            self._pending
        return self._publish(bundle, generation, step, series, decision,
                             mid_run=mid_run)

"""Exporters: JSON metrics snapshot + Chrome-trace timeline.

* :func:`metrics_snapshot` — the process-wide registry as one JSON-safe
  dict (the ``/metrics`` endpoint body, merged with per-model serve
  stats by the HTTP front end).
* :func:`chrome_trace` — captured spans/events as ``trace_event`` JSON
  (the Trace Event Format consumed by ``chrome://tracing`` and
  Perfetto's legacy importer): complete ``"ph": "X"`` events with
  microsecond ``ts``/``dur``, one ``tid`` lane per thread, span labels
  in ``args``. Thread-name metadata events give lanes readable names.
  Request-scoped trace ids (``obs/context.py``) additionally render as
  **flow events** (``ph: "s"/"t"/"f"``): one flow per request, stepping
  through every span that carries its trace id — so the fan-in of N
  admitted requests into one bucket-batch span and the fan-out back to
  their per-request completions draw as arrows across lanes.
  Host spans recorded while ``enable(device_annotations=True)`` also
  entered ``jax.profiler`` annotations, so a simultaneous XProf capture
  carries the same names on its device timeline — load both traces in
  Perfetto to correlate.
* :func:`prometheus_text` — one or more metrics registries in the
  Prometheus text exposition format (the ``/metrics`` endpoint body
  under ``Accept: text/plain`` content negotiation), so standard
  scrapers consume the same registry the JSON snapshot serves.
"""

from __future__ import annotations

import json
import math
import os
import re
from typing import Any

from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.events import EventRecord, SpanRecord
from mmlspark_tpu.obs.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, registry,
)


def metrics_snapshot() -> dict:
    """The default registry + tracer state, JSON-safe."""
    return {
        "enabled": _rt.enabled(),
        "captured_spans": _rt.captured_count(),
        "metrics": registry().snapshot(),
    }


def _args(labels: dict | None) -> dict:
    if not labels:
        return {}
    return {str(k): (v if isinstance(v, (int, float, str, bool))
                     or v is None else str(v))
            for k, v in labels.items()}


# serve-replica spans get their own synthetic timeline lane (one per
# (model, replica index)) so the DP fan-out's concurrency is visible
# directly — the base is far above any real thread id's useful range and
# stable across exports; the model digest keeps two sharded models'
# replica-0 lanes from colliding onto one tid (Perfetto derives span
# nesting from interval containment per tid)
REPLICA_TID_BASE = 1 << 31
_REPLICA_LANE_STRIDE = 4096


def _record_lane(r: Any) -> tuple[int, str]:
    """(tid, lane name) for one record: spans labeled with a ``replica``
    index land on a dedicated per-(model, replica) lane instead of their
    worker thread's, so a dp=N model renders as N parallel lanes."""
    labels = getattr(r, "labels", None)
    if labels:
        rep = labels.get("replica")
        if rep is not None:
            try:
                idx = int(rep)
            except (TypeError, ValueError):
                return r.tid, r.thread_name
            import zlib
            model = str(labels.get("model", ""))
            digest = zlib.crc32(model.encode("utf-8")) % _REPLICA_LANE_STRIDE
            tid = (REPLICA_TID_BASE + digest * _REPLICA_LANE_STRIDE
                   + idx % _REPLICA_LANE_STRIDE)
            name = (f"serve-replica-{idx}" if not model
                    else f"serve-replica-{idx} [{model}]")
            return tid, name
    return r.tid, r.thread_name


def chrome_trace(records: list | None = None) -> dict:
    """``{"traceEvents": [...]}`` for the given records (default: the
    runtime ring buffer). Spans become complete events (``ph: "X"``)
    whose nesting Perfetto derives from interval containment per
    ``tid``; instants become ``ph: "i"`` thread-scoped events.
    Replica-labeled serve spans render one lane per replica
    (:func:`_record_lane`), and request trace ids render as flow
    events (:func:`_flow_events`) so one request's journey draws as
    arrows across lanes."""
    if records is None:
        records = _rt.spans()
    pid = os.getpid()
    events: list[dict] = []
    thread_names: dict[int, str] = {}
    # trace id -> the spans carrying it (own trace or links), with the
    # lane each renders on — the flow-event pass below walks these
    flows: dict[int, list[tuple[SpanRecord, int]]] = {}
    for r in records:
        tid, lane = _record_lane(r)
        thread_names.setdefault(tid, lane)
        if isinstance(r, SpanRecord):
            events.append({
                "name": r.name, "cat": r.cat, "ph": "X",
                "ts": r.start_ns / 1e3, "dur": r.dur_ns / 1e3,
                "pid": pid, "tid": tid,
                "args": {**_args(r.labels), "span_id": r.span_id,
                         **({"parent_id": r.parent_id}
                            if r.parent_id is not None else {}),
                         **({"trace": r.trace}
                            if r.trace is not None else {}),
                         **({"links": list(r.links)} if r.links else {})},
            })
            if r.trace is not None:
                flows.setdefault(r.trace, []).append((r, tid))
            for link in r.links or ():
                flows.setdefault(link, []).append((r, tid))
        elif isinstance(r, EventRecord):
            events.append({
                "name": r.name, "cat": r.cat, "ph": "i", "s": "t",
                "ts": r.ts_ns / 1e3, "pid": pid, "tid": tid,
                "args": _args(r.labels),
            })
    events.extend(_flow_events(flows, pid))
    for tid, tname in thread_names.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": tname},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _flow_events(flows: dict[int, list[tuple[SpanRecord, int]]],
                 pid: int) -> list[dict]:
    """Perfetto flow events for the request traces: per trace id, a
    flow start (``ph: "s"``) anchored in its first span, a step
    (``"t"``) in every intermediate span, and a finish (``"f"``) in the
    last — each bound to its enclosing slice (``bp: "e"``, timestamp at
    the span's midpoint so the binding is unambiguous). In the Perfetto
    UI this draws the admission → pack → dispatch → drain → complete
    arrows of one request across the scheduler/lane/replica lanes —
    including the N-into-1 fan-in at pack and the 1-into-N fan-out at
    completion, because batch spans participate in every linked flow."""
    out: list[dict] = []
    for flow_id, touched in flows.items():
        if len(touched) < 2:
            continue  # an arrow needs two ends
        touched = sorted(touched, key=lambda t: (t[0].start_ns,
                                                 t[0].span_id))
        last = len(touched) - 1
        for i, (r, tid) in enumerate(touched):
            out.append({
                "name": "request", "cat": "serve.request",
                "ph": "s" if i == 0 else ("f" if i == last else "t"),
                "id": flow_id, "bp": "e",
                "ts": (r.start_ns + r.dur_ns / 2) / 1e3,
                "pid": pid, "tid": tid,
            })
    return out


def write_chrome_trace(path: str, records: list | None = None) -> str:
    """Serialize :func:`chrome_trace` to ``path``; returns the path."""
    payload = chrome_trace(records)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    return path


def write_snapshot(path: str) -> str:
    """Serialize :func:`metrics_snapshot` to ``path``; returns the path."""
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(metrics_snapshot(), fh, indent=2, default=str)
    return path


def summarize_spans(records: list | None = None,
                    top: int = 20) -> list[dict]:
    """Aggregate spans by name: calls, total/mean ms — the CLI's text
    timeline (``tools/trace.py render``)."""
    if records is None:
        records = _rt.spans()
    agg: dict[str, dict[str, Any]] = {}
    for r in records:
        if not isinstance(r, SpanRecord):
            continue
        row = agg.setdefault(r.name, {"name": r.name, "cat": r.cat,
                                      "calls": 0, "total_ms": 0.0})
        row["calls"] += 1
        row["total_ms"] += r.dur_ns / 1e6
    rows = sorted(agg.values(), key=lambda d: -d["total_ms"])[:top]
    for row in rows:
        row["total_ms"] = round(row["total_ms"], 3)
        row["mean_ms"] = round(row["total_ms"] / row["calls"], 3)
    return rows


# ---- Prometheus text exposition (the /metrics content-negotiated body) ----

_PROM_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")

# registry-series name → HELP text. Keyed by the ORIGINAL (dotted)
# name; anything not listed falls back to a generic line, so every
# family always carries a HELP/TYPE pair (some scrapers and linters —
# promtool check metrics — warn on HELP-less families). Keep entries
# one-line: the exposition format ends HELP at the newline.
METRIC_HELP: dict[str, str] = {
    "plan.h2d_uploads": "Host-to-device uploads issued by the device "
                        "plan executor (one per fused-segment entry).",
    "plan.h2d_bytes": "Bytes shipped host-to-device at the plan's "
                      "upload seam.",
    "plan.d2h_fetches": "Async device-to-host fetch rounds issued by "
                        "the plan executor.",
    "plan.d2h_bytes": "Bytes fetched device-to-host at the plan's "
                      "fetch seam.",
    "plan.segment_compiles": "Fresh XLA compilations observed at the "
                             "plan dispatch seam.",
    "serve.queue_depth": "Live admission-queue depth (the replica "
                         "autoscaling signal).",
    "serve.slo_burn_short": "Error-budget burn multiple over the SLO's "
                            "short window (fast-burn page signal).",
    "serve.slo_burn_long": "Error-budget burn multiple over the SLO's "
                           "long window (sustained degradation).",
    "serve.slo_budget_remaining": "Fraction of the SLO error budget "
                                  "remaining (lifetime).",
    "serve.occupancy_mean_window": "Mean batch occupancy over the SLO "
                                   "sample window (adaptive-ladder "
                                   "signal).",
    "serve.replica_skew": "DP replica load imbalance: (max-min)/max "
                          "over per-replica batch counts.",
    "train.steps": "Optimizer steps completed by the training loop.",
    "train.step_ms": "Per-step dispatch time of the training loop.",
    "train.host_step_ms": "Per-host mean step time from the fenced "
                          "liveness exchange (straggler sensor).",
    "train.host_skew": "Max/median host step-time skew across the "
                       "training fleet.",
    "train.slow_steps": "Steps flagged slower than factor x the "
                        "rolling median.",
    "train.fleet.workers": "Live supervised workers reporting a "
                           "current-generation beacon.",
    "train.fleet.progress": "Summed progress (heartbeats + steps) "
                            "across the supervised fleet.",
    "train.fleet.straggler_windows": "Global straggler verdict windows "
                                     "this generation (max across "
                                     "beacons).",
    "train.fleet.host_step_ms": "Per-host step time as aggregated by "
                                "the supervisor from worker beacons.",
    "flight.dumps": "Post-mortem dumps written by the flight recorder.",
    "obs.traces_dropped": "Request traces evicted by the retention "
                          "policy.",
}


def _prom_help(original_name: str) -> str:
    text = METRIC_HELP.get(original_name)
    if text is None:
        # generic fallback: every family gets SOME help line, and the
        # original dotted spelling survives sanitization for operators
        # grepping the codebase
        text = f"mmlspark_tpu metric {original_name} (see " \
               "docs/observability.md)."
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _prom_name(name: str) -> str:
    """Registry series name → a legal Prometheus metric name (dots and
    other separators become underscores; a leading digit is prefixed)."""
    name = _PROM_NAME_BAD.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_labels(labels: tuple, extra: tuple = ()) -> str:
    """``(k, v)`` label pairs → ``{k="v",...}`` with value escaping per
    the exposition format (backslash, quote, newline)."""
    pairs = tuple(labels) + tuple(extra)
    if not pairs:
        return ""
    parts = []
    for k, v in pairs:
        val = str(v).replace("\\", r"\\").replace('"', r"\"")
        val = val.replace("\n", r"\n")
        parts.append(f'{_prom_name(str(k))}="{val}"')
    return "{" + ",".join(parts) + "}"


def _prom_value(v: float) -> str:
    v = float(v)
    if math.isnan(v):
        # the registry is the shared substrate — one client recording a
        # NaN/Inf (zero-denominator ratio, say) must not 500 the whole
        # scrape; these are the official Prometheus text literals
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v):
        return str(int(v))
    return repr(v)


def prometheus_text(registries: list[MetricsRegistry] | None = None) -> str:
    """Every metric of the given registries (default: the process-wide
    one) in the Prometheus text exposition format (version 0.0.4).

    Counters/gauges map directly; histograms expose as summaries —
    ``name{quantile="0.5|0.95|0.99"}`` over the bounded window plus the
    exact lifetime ``name_count``/``name_sum``. ONE ``# HELP``/``# TYPE``
    header pair is emitted per metric name across all registries
    (per-model serve registries — and the fleet-merged per-host
    registries — contribute the same names under different labels;
    repeating a header per registry is an exposition-format violation
    scrapers reject). HELP text comes from :data:`METRIC_HELP` with a
    generic fallback, so every family is self-describing. Unset gauges
    are skipped (Prometheus has no null). Series within a name are
    emitted in sorted order so consecutive scrapes of the same state
    are byte-identical."""
    if registries is None:
        registries = [registry()]
    # prom name -> [type string, [(series text, value)], original name]
    by_name: dict[str, list] = {}

    def _add(name: str, original: str, kind: str,
             lines: list[tuple[str, str]]) -> None:
        slot = by_name.setdefault(name, [kind, [], original])
        slot[1].extend(lines)

    for reg in registries:
        for m in reg.iter_metrics():
            name = _prom_name(m.name)
            if isinstance(m, Counter):
                _add(name, m.name, "counter",
                     [(f"{name}{_prom_labels(m.labels)}",
                       _prom_value(m.value))])
            elif isinstance(m, Gauge):
                v = m.value
                if v is None:
                    continue
                _add(name, m.name, "gauge",
                     [(f"{name}{_prom_labels(m.labels)}",
                       _prom_value(v))])
            elif isinstance(m, Histogram):
                pct = m.percentiles(ndigits=None)
                lines = []
                if pct is not None:
                    for q, key in (("0.5", "p50"), ("0.95", "p95"),
                                   ("0.99", "p99")):
                        lines.append((
                            f"{name}"
                            f"{_prom_labels(m.labels, (('quantile', q),))}",
                            _prom_value(pct[key])))
                lines.append((f"{name}_count{_prom_labels(m.labels)}",
                              _prom_value(m.count)))
                lines.append((f"{name}_sum{_prom_labels(m.labels)}",
                              _prom_value(m.sum)))
                _add(name, m.name, "summary", lines)
    chunks: list[str] = []
    for name in sorted(by_name):
        kind, lines, original = by_name[name]
        chunks.append(f"# HELP {name} {_prom_help(original)}")
        chunks.append(f"# TYPE {name} {kind}")
        chunks.extend(f"{series} {value}" for series, value
                      in sorted(lines))
    return "\n".join(chunks) + ("\n" if chunks else "")

"""Exporters: JSON metrics snapshot + Chrome-trace timeline.

* :func:`metrics_snapshot` — the process-wide registry as one JSON-safe
  dict (the ``/metrics`` endpoint body, merged with per-model serve
  stats by the HTTP front end).
* :func:`chrome_trace` — captured spans/events as ``trace_event`` JSON
  (the Trace Event Format consumed by ``chrome://tracing`` and
  Perfetto's legacy importer): complete ``"ph": "X"`` events with
  microsecond ``ts``/``dur``, one ``tid`` lane per thread, span labels
  in ``args``. Thread-name metadata events give lanes readable names.
  Host spans recorded while ``enable(device_annotations=True)`` also
  entered ``jax.profiler`` annotations, so a simultaneous XProf capture
  carries the same names on its device timeline — load both traces in
  Perfetto to correlate.
"""

from __future__ import annotations

import json
import os
from typing import Any

from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.events import EventRecord, SpanRecord
from mmlspark_tpu.obs.metrics import registry


def metrics_snapshot() -> dict:
    """The default registry + tracer state, JSON-safe."""
    return {
        "enabled": _rt.enabled(),
        "captured_spans": _rt.captured_count(),
        "metrics": registry().snapshot(),
    }


def _args(labels: dict | None) -> dict:
    if not labels:
        return {}
    return {str(k): (v if isinstance(v, (int, float, str, bool))
                     or v is None else str(v))
            for k, v in labels.items()}


# serve-replica spans get their own synthetic timeline lane (one per
# (model, replica index)) so the DP fan-out's concurrency is visible
# directly — the base is far above any real thread id's useful range and
# stable across exports; the model digest keeps two sharded models'
# replica-0 lanes from colliding onto one tid (Perfetto derives span
# nesting from interval containment per tid)
REPLICA_TID_BASE = 1 << 31
_REPLICA_LANE_STRIDE = 4096


def _record_lane(r: Any) -> tuple[int, str]:
    """(tid, lane name) for one record: spans labeled with a ``replica``
    index land on a dedicated per-(model, replica) lane instead of their
    worker thread's, so a dp=N model renders as N parallel lanes."""
    labels = getattr(r, "labels", None)
    if labels:
        rep = labels.get("replica")
        if rep is not None:
            try:
                idx = int(rep)
            except (TypeError, ValueError):
                return r.tid, r.thread_name
            import zlib
            model = str(labels.get("model", ""))
            digest = zlib.crc32(model.encode("utf-8")) % _REPLICA_LANE_STRIDE
            tid = (REPLICA_TID_BASE + digest * _REPLICA_LANE_STRIDE
                   + idx % _REPLICA_LANE_STRIDE)
            name = (f"serve-replica-{idx}" if not model
                    else f"serve-replica-{idx} [{model}]")
            return tid, name
    return r.tid, r.thread_name


def chrome_trace(records: list | None = None) -> dict:
    """``{"traceEvents": [...]}`` for the given records (default: the
    runtime ring buffer). Spans become complete events (``ph: "X"``)
    whose nesting Perfetto derives from interval containment per
    ``tid``; instants become ``ph: "i"`` thread-scoped events.
    Replica-labeled serve spans render one lane per replica
    (:func:`_record_lane`)."""
    if records is None:
        records = _rt.spans()
    pid = os.getpid()
    events: list[dict] = []
    thread_names: dict[int, str] = {}
    for r in records:
        tid, lane = _record_lane(r)
        thread_names.setdefault(tid, lane)
        if isinstance(r, SpanRecord):
            events.append({
                "name": r.name, "cat": r.cat, "ph": "X",
                "ts": r.start_ns / 1e3, "dur": r.dur_ns / 1e3,
                "pid": pid, "tid": tid,
                "args": {**_args(r.labels), "span_id": r.span_id,
                         **({"parent_id": r.parent_id}
                            if r.parent_id is not None else {})},
            })
        elif isinstance(r, EventRecord):
            events.append({
                "name": r.name, "cat": r.cat, "ph": "i", "s": "t",
                "ts": r.ts_ns / 1e3, "pid": pid, "tid": tid,
                "args": _args(r.labels),
            })
    for tid, tname in thread_names.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": tname},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, records: list | None = None) -> str:
    """Serialize :func:`chrome_trace` to ``path``; returns the path."""
    payload = chrome_trace(records)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    return path


def write_snapshot(path: str) -> str:
    """Serialize :func:`metrics_snapshot` to ``path``; returns the path."""
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(metrics_snapshot(), fh, indent=2, default=str)
    return path


def summarize_spans(records: list | None = None,
                    top: int = 20) -> list[dict]:
    """Aggregate spans by name: calls, total/mean ms — the CLI's text
    timeline (``tools/trace.py render``)."""
    if records is None:
        records = _rt.spans()
    agg: dict[str, dict[str, Any]] = {}
    for r in records:
        if not isinstance(r, SpanRecord):
            continue
        row = agg.setdefault(r.name, {"name": r.name, "cat": r.cat,
                                      "calls": 0, "total_ms": 0.0})
        row["calls"] += 1
        row["total_ms"] += r.dur_ns / 1e6
    rows = sorted(agg.values(), key=lambda d: -d["total_ms"])[:top]
    for row in rows:
        row["total_ms"] = round(row["total_ms"], 3)
        row["mean_ms"] = round(row["total_ms"] / row["calls"], 3)
    return rows

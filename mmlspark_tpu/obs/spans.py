"""The span/event tracer — nested, thread-aware, near-zero when off.

Usage at an instrumented seam::

    from mmlspark_tpu.obs import span, event

    with span("plan/fused_segment", "plan", {"rows": n}):
        ...
    event("serve/overloaded", "serve")

Disabled (the default), :func:`span` is ONE module-flag check returning a
shared null context — no record, no allocation beyond the call itself.
Enabled, each span captures wall-clock start/duration
(``time.perf_counter_ns``), the owning thread, and its parent span on
that thread (a thread-local stack), then lands in the bounded ring
buffer (:mod:`~mmlspark_tpu.obs.runtime`). Exceptions propagate —
tracing never swallows an error — and the span still records, so a
timeline shows where a run died.

With ``enable(device_annotations=True)`` each span also enters
``jax.profiler.TraceAnnotation`` (via ``utils/profiling.annotate``), so
an XProf/Perfetto device capture shows the same names on its host track,
interleaved with the device ops dispatched under them.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any

from mmlspark_tpu.obs import context as _ctx
from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.events import EventRecord, SpanRecord

_tls = threading.local()
_ids = itertools.count(1)  # CPython-atomic id source


class _NullSpan:
    """Shared do-nothing context for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL = _NullSpan()


def _annotation(name: str):
    """A jax profiler annotation, or None when jax is unavailable — the
    tracer must stay importable and usable on host-only processes."""
    try:
        from mmlspark_tpu.utils.profiling import annotate
        return annotate(name)
    except Exception:  # pragma: no cover - jax present throughout CI
        return None


class _Span:
    __slots__ = ("name", "cat", "labels", "links", "_t0", "_span_id",
                 "_parent", "_depth", "_trace", "_annot")

    def __init__(self, name: str, cat: str, labels: dict | None,
                 links: tuple | None = None):
        self.name = name
        self.cat = cat
        self.labels = labels
        self.links = links

    def __enter__(self) -> "_Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._span_id = next(_ids)
        self._parent = stack[-1] if stack else None
        self._depth = len(stack)
        stack.append(self._span_id)
        # the thread's active request context (obs/context.bind): spans
        # recorded while a trace is bound belong to that request
        self._trace = _ctx.current()
        self._annot = None
        if _rt._device_annotations:
            annot = _annotation(self.name)
            if annot is not None:
                annot.__enter__()
                self._annot = annot
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        dur = time.perf_counter_ns() - self._t0
        if self._annot is not None:
            self._annot.__exit__(*exc)
        stack = _tls.stack
        if stack and stack[-1] == self._span_id:
            stack.pop()
        th = threading.current_thread()
        _rt.record(SpanRecord(self.name, self.cat, self._t0, dur,
                              th.ident or 0, th.name, self._span_id,
                              self._parent, self._depth, self.labels,
                              self._trace, self.links))
        return False


def span(name: str, cat: str = "host", labels: dict | None = None,
         links: tuple | None = None) -> Any:
    """Context manager tracing one interval; a shared no-op when the
    tracer is disabled (``labels``/``links`` are plain parameters, not
    ``**kwargs``, so the disabled call allocates nothing). ``links`` is
    the fan-in edge set: the trace ids of every request this span works
    for at once (obs/context.py)."""
    if not _rt._enabled:
        return _NULL
    return _Span(name, cat, labels, links)


def event(name: str, cat: str = "host",
          labels: dict | None = None) -> None:
    """Record one instant event (no interval); no-op when disabled."""
    if not _rt._enabled:
        return
    th = threading.current_thread()
    _rt.record(EventRecord(name, cat, time.perf_counter_ns(),
                           th.ident or 0, th.name, labels))

"""Device attribution — compiled-program cost, memory accounting, and the
compute/transfer/idle split.

PR 5 made the crossing *counts* observable (every H2D/D2H through the
plan seams lands in the registry), but the device itself stayed dark:
what did each compiled segment cost to build, how much HBM does it
touch, and how much of a step's wall clock is compute versus transfer
versus host idle? This module is that accounting, in three pieces, all
recorded through the shared registry (the one-substrate rule):

* **compile attribution** (:func:`note_dispatch`) — the plan dispatch
  seam calls it after every program invocation when the pillar is on.
  A fresh XLA compile is detected by compile-cache growth (the obs-owned
  ``jit_cache_size`` hook, extended from a lifetime count to a
  per-dispatch delta; first-seen-shape memo when the jit object hides
  its cache), and attributed as one ``plan.compile_ms{segment=…}``
  histogram observation plus a ``plan.xla_compiles{segment=…}`` count —
  compile-time histograms keyed by segment and entry bucket.
* **cost/memory capture** (:func:`_capture_cost`) — once per
  ``(program, entry shape)`` the same program is AOT-lowered and
  compiled so XLA's own ``cost_analysis``/``memory_analysis`` can be
  read (the dispatch cache's executable is not introspectable, so this
  is a second compile of an identical program — the documented price of
  the opt-in pillar; the plan seam calls it *outside* the
  ``plan/dispatch`` span so the recompile lands in the split's idle
  time, never its compute), populating ``plan.segment.flops``,
  ``plan.segment.bytes`` and ``plan.segment.peak_hbm`` gauges keyed by
  ``{segment=…, shape=…}``. ``peak_hbm`` prefers the backend's
  ``memory_analysis`` (argument + output + temp buffers); backends that
  do not report it (the CPU dryrun mesh) fall back to the cost model's
  ``bytes accessed`` so the gauge is always populated.
* **live memory** (:func:`poll_memory`) — ``device.memory_stats()``
  where the backend exposes it (TPU/GPU), published as
  ``device.mem_bytes_in_use{device=…}`` / ``device.mem_peak_bytes`` /
  ``device.mem_limit_bytes`` gauges; dryrun/CPU devices return nothing
  and the poll is a cheap no-op (never an error, never a jax init).
* **timeline split** (:func:`device_time_split`) — the honest
  compute/transfer/idle decomposition of a captured run, derived from
  the *existing* ``plan/dispatch``/``plan/h2d``/``plan/d2h`` spans (no
  new seams): dispatch intervals minus their nested H2D time are
  compute-issue, D2H drains are transfer, and whatever the wall clock
  holds beyond both is host idle. This is what ``bench.py`` reports
  next to rows/s, so "input-bound" claims are backed by attribution.

The pillar is OFF by default and independent of the tracer flag:
``obs.enable(device=True)`` (or ``MMLSPARK_TPU_OBS_DEVICE=1``) turns it
on along with ``jax.profiler`` device annotations. Disabled, the plan
seam pays one extra attribute check per dispatched minibatch — inside
the < 2% ``check_obs_overhead`` budget.
"""

from __future__ import annotations

import sys
import threading
import weakref
from typing import Any

from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.metrics import registry as _registry

# the device-attribution pillar flag — mutate only through
# enable()/disable() (obs.runtime.enable(device=True) routes here)
_enabled = False


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


# per-jitted-program set of entry shapes already attributed. WeakKey so a
# segment evicted from the plan cache releases its memo with it
_seen: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_seen_lock = threading.Lock()


def reset() -> None:
    """Drop the per-program attribution memos (test isolation)."""
    with _seen_lock:
        _seen.clear()


def note_dispatch(fn: Any, dev_params: Any, chunk: Any,
                  label: str | None, cache_before: int | None,
                  dur_s: float) -> None:
    """Attribute one program invocation at the plan dispatch seam.

    ``cache_before`` is ``jit_cache_size(fn)`` read before the call;
    growth afterwards means the call included an XLA compile and its
    duration is the compile time (dispatch issue is sub-ms next to any
    real compile). Jit objects without a readable cache fall back to a
    first-seen-shape memo. Attribution must never break dispatch — any
    failure here is swallowed."""
    try:
        shape = tuple(getattr(chunk, "shape", ()))
        after = _rt.jit_cache_size(fn)
        with _seen_lock:
            shapes = _seen.get(fn)
            if shapes is None:
                shapes = _seen[fn] = set()
            first = shape not in shapes
            shapes.add(shape)
        fresh = (after > cache_before
                 if cache_before is not None and after is not None
                 else first)
        if not (fresh or first):
            return
        seg = label or "segment"
        reg = _registry()
        if fresh:
            reg.counter("plan.xla_compiles", segment=seg).add()
            reg.histogram("plan.compile_ms",
                          segment=seg).observe(dur_s * 1e3)
        if first:
            # cost capture keys on the per-process memo, not on cache
            # growth: a program compiled before the pillar was enabled
            # (bench warms, then traces) still gets its cost/memory
            # gauges — only the compile TIME is unknowable then
            _capture_cost(fn, dev_params, chunk, seg, shape, reg)
    except Exception:  # pragma: no cover - attribution is best-effort
        pass


def _capture_cost(fn: Any, dev_params: Any, chunk: Any, seg: str,
                  shape: tuple, reg: Any) -> None:
    """AOT-compile ``fn`` at this entry shape and publish XLA's cost and
    memory analyses as ``plan.segment.*`` gauges."""
    import jax

    sds = jax.ShapeDtypeStruct(tuple(chunk.shape), chunk.dtype)
    compiled = fn.lower(dev_params, sds).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    cost = cost or {}
    lbl = {"segment": seg, "shape": str(shape)}
    flops = cost.get("flops")
    if flops is not None:
        reg.gauge("plan.segment.flops", **lbl).set(float(flops))
    nbytes = cost.get("bytes accessed")
    if nbytes is not None:
        reg.gauge("plan.segment.bytes", **lbl).set(float(nbytes))
    peak = None
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        try:
            peak = float(mem.argument_size_in_bytes
                         + mem.output_size_in_bytes
                         + mem.temp_size_in_bytes)
        except Exception:
            peak = None
    if peak is None:
        # dryrun-safe fallback: the cost model's total bytes touched is
        # the best available stand-in, so the gauge is always populated
        peak = float(nbytes) if nbytes is not None else 0.0
    reg.gauge("plan.segment.peak_hbm", **lbl).set(peak)


def poll_memory(reg: Any = None) -> dict:
    """Publish live/peak device-memory gauges from ``memory_stats()``.

    Returns ``{device_key: stats}`` for devices that report; empty on
    backends without memory stats (the CPU dryrun mesh) and when jax was
    never imported (polling must not initialize a backend — the flight
    watchdog calls this from its own thread)."""
    if "jax" not in sys.modules:
        return {}
    import jax

    # "jax imported" is NOT "backend initialized": jax.local_devices()
    # would INITIALIZE the default backend — fatal for an app that
    # imports jax early but calls jax.distributed.initialize() later
    # (the poll would lock it into single-process mode / grab HBM).
    # Poll only once the app itself has brought a backend up.
    try:
        from jax._src import xla_bridge as _xb
        initialized = (_xb.backends_are_initialized()
                       if hasattr(_xb, "backends_are_initialized")
                       else bool(getattr(_xb, "_backends", None)))
    except Exception:  # pragma: no cover - private-API drift
        initialized = False
    if not initialized:
        return {}

    reg = reg if reg is not None else _registry()
    out: dict = {}
    try:
        devices = jax.local_devices()
    except Exception:  # pragma: no cover - backend not initialized
        return {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        key = f"{d.platform}:{getattr(d, 'id', 0)}"
        used = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        if used is not None:
            reg.gauge("device.mem_bytes_in_use", device=key).set(used)
        if peak is not None:
            reg.gauge("device.mem_peak_bytes", device=key).set(peak)
        if limit is not None:
            reg.gauge("device.mem_limit_bytes", device=key).set(limit)
        out[key] = {"bytes_in_use": used, "peak_bytes_in_use": peak,
                    "bytes_limit": limit}
    return out


# span names the timeline split classifies (all pre-existing seams)
_DISPATCH_SPANS = ("plan/dispatch",)
_H2D_SPANS = ("plan/h2d",)
_D2H_SPANS = ("plan/d2h",)


def _union(intervals: list) -> list:
    """Merge ``(start, end)`` intervals into a disjoint, sorted union."""
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _measure(intervals: list) -> float:
    return float(sum(e - s for s, e in intervals))


def _subtract(base: list, cut: list) -> list:
    """``base`` minus ``cut``, both disjoint sorted unions."""
    out = []
    for s, e in base:
        for cs, ce in cut:
            if ce <= s or cs >= e:
                continue
            if cs > s:
                out.append((s, cs))
            s = max(s, min(ce, e))
            if s >= e:
                break
        if s < e:
            out.append((s, e))
    return out


def device_time_split(records: list | None = None) -> dict | None:
    """Compute/transfer/idle attribution of a captured run's plan spans.

    Host-side attribution over the UNION of span intervals — concurrent
    serve lanes (dp>1) emit overlapping ``plan/dispatch`` spans, and a
    naive per-span duration sum would report compute > wall and
    fractions > 1. Attribution precedence inside the occupied union:
    ``plan/h2d`` is transfer, ``plan/dispatch`` time not spent in its
    nested h2d is compute-issue, ``plan/d2h`` time outside both is the
    blocking device→host drains, and ``idle`` is the wall clock no plan
    span covers — the time the host spent between device work (packing,
    queue waits, python). Single-threaded captures decompose exactly as
    a per-span sum would. ``None`` when the capture holds no plan
    spans. Returns milliseconds plus fractions of wall (which now
    always sum to 1)."""
    from mmlspark_tpu.obs.events import SpanRecord

    by_kind: dict[str, list] = {"dispatch": [], "h2d": [], "d2h": []}
    if records is None:
        records = _rt.spans()
    for r in records:
        if not isinstance(r, SpanRecord) or r.cat != "plan":
            continue
        if r.name in _DISPATCH_SPANS:
            by_kind["dispatch"].append((r.start_ns, r.end_ns))
        elif r.name in _H2D_SPANS:
            by_kind["h2d"].append((r.start_ns, r.end_ns))
        elif r.name in _D2H_SPANS:
            by_kind["d2h"].append((r.start_ns, r.end_ns))
    all_iv = by_kind["dispatch"] + by_kind["h2d"] + by_kind["d2h"]
    if not all_iv:
        return None
    u_h2d = _union(by_kind["h2d"])
    u_disp = _union(by_kind["dispatch"])
    u_d2h = _union(by_kind["d2h"])
    wall = max(e for _, e in all_iv) - min(s for s, _ in all_iv)
    h2d = _measure(u_h2d)
    compute = _measure(_subtract(u_disp, u_h2d))
    d2h = _measure(_subtract(_subtract(u_d2h, u_disp), u_h2d))
    idle = max(wall - (compute + h2d + d2h), 0.0)
    out = {
        "wall_ms": round(wall / 1e6, 3),
        "compute_ms": round(compute / 1e6, 3),
        "h2d_ms": round(h2d / 1e6, 3),
        "d2h_ms": round(d2h / 1e6, 3),
        "idle_ms": round(idle / 1e6, 3),
    }
    if wall > 0:
        for key in ("compute", "h2d", "d2h", "idle"):
            out[f"{key}_fraction"] = round(out[f"{key}_ms"] * 1e6 / wall, 4)
    return out

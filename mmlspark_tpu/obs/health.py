"""Health surfaces — the ok/degraded/unhealthy state machine.

Burn rates and latency verdicts (:mod:`mmlspark_tpu.obs.slo`) are
instantaneous signals; a health endpoint needs a *state* that neither
flaps on one bad sample nor lingers green through a sustained burn.
This module is that state machine, deliberately tiny and deterministic:

* **classification** (:func:`classify`) maps one SLO status dict to a
  level — ``unhealthy`` when the short-window burn crosses the
  fast-burn threshold or admission is bouncing a majority of arrivals
  (the reject-ratio rule: ``Overloaded`` is backpressure, and sustained
  backpressure is an unhealthy service even while completed requests
  still succeed); ``degraded`` on sustained long-window burn or a
  violated latency objective backed by fresh short-window traffic (the
  e2e reservoir freezes when traffic stops — a stale spike must not
  hold the verdict); ``ok`` otherwise.
* **hysteresis** (:class:`HealthMonitor`): worsening applies
  immediately (a page must not wait), improving requires
  ``recover_after`` consecutive better samples (a flapping service is
  not healthy).

Readiness is the health state plus **drain-awareness**: a draining
server (or model) reports itself not-ready so load balancers stop
sending traffic. Liveness is deliberately NOT derived from any of
this — ``/livez`` answers 200 whenever the process serves HTTP, so an
alive-but-burning or draining server fails readiness without getting
restarted. The ``/healthz``/``/livez``/``/slo`` wiring lives in
``serve/server.py`` + ``serve/http.py``.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

OK = "ok"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"

SEVERITY = {OK: 0, DEGRADED: 1, UNHEALTHY: 2}


def worst(states: list[str]) -> str:
    """The most severe of a set of states (``ok`` for an empty set —
    a server with no models is trivially healthy)."""
    return max(states, key=SEVERITY.__getitem__, default=OK)


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Thresholds of the state machine. ``fast_burn``/``slow_burn``
    default from the SLO spec that drives the monitor;
    ``reject_ratio`` is the fraction of window arrivals bounced by
    admission control at which the model is unhealthy regardless of
    burn (needs ``min_events`` arrivals for a verdict);
    ``recover_after`` is the hysteresis depth — consecutive
    better-level samples required before the state improves."""

    fast_burn: float = 14.0
    slow_burn: float = 2.0
    reject_ratio: float = 0.5
    min_events: int = 10
    recover_after: int = 3


def classify(status: dict, policy: HealthPolicy) -> tuple[str, str]:
    """(level, reason) for one :meth:`SLOTracker.sample` status dict.
    Pure function of the status — the monitor owns the memory."""
    burn_short = status.get("burn_rate_short")
    if burn_short is not None and burn_short >= policy.fast_burn:
        return UNHEALTHY, (
            f"short-window burn {burn_short:.1f}x >= "
            f"{policy.fast_burn:g}x budget")
    short = status.get("window_short") or {}
    arrivals = (short.get("admitted") or 0) + (short.get("rejected") or 0)
    if arrivals >= policy.min_events:
        ratio = (short.get("rejected") or 0) / arrivals
        if ratio >= policy.reject_ratio:
            return UNHEALTHY, (
                f"admission rejecting {ratio:.0%} of arrivals "
                f"(>= {policy.reject_ratio:.0%})")
    burn_long = status.get("burn_rate_long")
    if burn_long is not None and burn_long >= policy.slow_burn:
        return DEGRADED, (
            f"long-window burn {burn_long:.1f}x >= "
            f"{policy.slow_burn:g}x budget")
    if status.get("latency_ok") is False \
            and (short.get("terminal") or 0) >= policy.min_events:
        # the e2e reservoir freezes when traffic stops, so a latency
        # violation only counts while the short window carries fresh
        # terminal traffic (the burn verdicts' no-traffic rule) —
        # otherwise one cold-compile spike would hold DEGRADED forever,
        # with the hysteresis recovery never able to fire
        spec = status.get("slo") or {}
        return DEGRADED, (
            f"latency {status.get('latency_ms'):.1f} ms exceeds the "
            f"{spec.get('latency_quantile', 'p99')} objective "
            f"{spec.get('latency_ms')} ms")
    return OK, ""


class HealthMonitor:
    """Hysteretic health state of one served model.

    ``update(status)`` classifies the sample and advances the state:
    a worse level applies immediately; a better level must be observed
    ``recover_after`` times in a row — at that SAME level — before the
    state steps down to it (a worse sample, or a different better
    level, resets the streak; UNHEALTHY cannot jump straight to OK on
    one quiet sample after a run of DEGRADED ones).
    ``state``/``reason`` are the last-transition verdict the health
    surfaces expose.
    """

    __slots__ = ("policy", "state", "reason", "_streak", "_candidate",
                 "_lock")

    def __init__(self, policy: HealthPolicy | None = None):
        self.policy = policy or HealthPolicy()
        self.state = OK
        self.reason = ""
        self._streak = 0
        self._candidate: str | None = None
        # /healthz and /slo handler threads advance the same monitor;
        # an unsynchronized read-modify-write of the streak would let
        # two concurrent good samples count as recover_after progress
        # twice (or lose a worsening transition)
        self._lock = threading.Lock()

    @classmethod
    def for_spec(cls, spec: Any) -> "HealthMonitor":
        """A monitor whose burn thresholds come from an
        :class:`~mmlspark_tpu.obs.slo.SLOSpec`."""
        return cls(HealthPolicy(fast_burn=spec.fast_burn,
                                slow_burn=spec.slow_burn,
                                min_events=spec.min_requests))

    def update(self, status: dict) -> str:
        return self.update_describe(status)["state"]

    def update_describe(self, status: dict) -> dict:
        """Advance the machine and return ``{state, reason}`` from the
        SAME locked transition — pairing :meth:`update` with a later
        read of ``.reason`` can interleave with a concurrent poller's
        transition and report one verdict's state with another's
        reason."""
        level, reason = classify(status, self.policy)
        with self._lock:
            if SEVERITY[level] > SEVERITY[self.state]:
                self.state, self.reason = level, reason
                self._streak, self._candidate = 0, None
            elif level == self.state:
                self._streak, self._candidate = 0, None
                if reason:
                    self.reason = reason
            else:
                if level != self._candidate:
                    self._candidate, self._streak = level, 1
                else:
                    self._streak += 1
                if self._streak >= self.policy.recover_after:
                    self.state, self.reason = level, reason
                    self._streak, self._candidate = 0, None
            return {"state": self.state, "reason": self.reason}

    def describe(self) -> dict:
        return {"state": self.state, "reason": self.reason}

"""Runtime lock-order witness — the dynamic half of the concurrency
verifier (:mod:`mmlspark_tpu.analysis.concurrency`).

The static analyzer predicts a lock-order graph from the AST; this
module *observes* the real one.  Hot locks are created through the
named factories::

    self._cv = named_condition("serve.batcher.DynamicBatcher._cv")
    self._lock = named_lock("obs.metrics.Counter._lock")

The name is the same canonical identity the static pass derives
(``<module>.<Class>.<attr>``), so the two graphs join on it — the
analyzer treats the string literal passed to a factory as the lock's
identity.  While the witness is **enabled**, every acquisition records
one edge ``held -> acquired`` per lock currently held by the acquiring
thread (thread-local held stacks).  :func:`crosscheck` then labels each
static edge:

* **CONFIRMED** — observed at runtime (the same adversarial posture as
  the SPMD verifier's predicted == lowered check), or
* **PLAUSIBLE** — statically derivable but never seen,

and reports **violations**: edge pairs observed in *both* directions —
a lock-order inversion actually executed, the runtime shadow of a
CC101 finding.

Cost discipline (PR 5): when disabled — the default — each lock
operation pays exactly one module-flag check on top of the raw
``threading`` primitive; ``check_concurrency_clean`` holds that under
the same 2% analytic bound as ``check_obs_overhead``.  Edge counters
are plain dict writes under the GIL (a lost increment under a race is
acceptable for a witness; edge *existence* is what is cross-checked).

On/off semantics, the inventory of witnessed locks, and the gate
wiring are documented in docs/concurrency.md.
"""

from __future__ import annotations

import threading

_enabled = False
_tls = threading.local()
_edges: dict[tuple[str, str], int] = {}
_acquires: dict[str, int] = {}


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Start recording acquisition edges (clears previous data)."""
    global _enabled
    reset()
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    _edges.clear()
    _acquires.clear()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _note_acquire(name: str) -> None:
    st = _stack()
    _acquires[name] = _acquires.get(name, 0) + 1
    for held in st:
        if held != name:
            key = (held, name)
            _edges[key] = _edges.get(key, 0) + 1
    st.append(name)


def _note_release(name: str) -> None:
    st = _stack()
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            return


def _note_release_all(name: str) -> None:
    st = _stack()
    st[:] = [n for n in st if n != name]


class _Witnessed:
    """Lock wrapper: delegates to a raw threading primitive, noting
    acquisition edges when the witness is enabled (one flag check on
    the disabled path)."""

    __slots__ = ("name", "_lk")

    def __init__(self, name: str, lk):
        self.name = name
        self._lk = lk

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lk.acquire(blocking, timeout)
        if ok and _enabled:
            _note_acquire(self.name)
        return ok

    def release(self) -> None:
        if _enabled:
            _note_release(self.name)
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._lk.locked()

    def __repr__(self):
        return f"<witnessed {self._lk!r} name={self.name!r}>"


class _WitnessedR(_Witnessed):
    """RLock wrapper; also speaks Condition's save/restore protocol so
    ``threading.Condition`` built over it waits correctly through
    re-entrant ownership."""

    __slots__ = ()

    def _release_save(self):
        if _enabled:
            _note_release_all(self.name)
        return self._lk._release_save()

    def _acquire_restore(self, state) -> None:
        self._lk._acquire_restore(state)
        if _enabled:
            _note_acquire(self.name)

    def _is_owned(self) -> bool:
        return self._lk._is_owned()

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        if self._lk.acquire(blocking=False):
            self._lk.release()
            return False
        return True


def named_lock(name: str) -> _Witnessed:
    """A ``threading.Lock`` with a canonical identity the witness (and
    the static analyzer) track."""
    return _Witnessed(name, threading.Lock())


def named_rlock(name: str) -> _WitnessedR:
    return _WitnessedR(name, threading.RLock())


def named_condition(name: str) -> threading.Condition:
    """A ``threading.Condition`` whose mutex is a witnessed RLock (the
    same default backing as ``threading.Condition()``).  ``wait()``
    releases and re-acquires through the wrapper, so held stacks stay
    truthful across waits."""
    return threading.Condition(named_rlock(name))


# -- reporting --------------------------------------------------------------


def edges() -> dict[tuple[str, str], int]:
    """Observed acquisition edges -> approximate counts."""
    return dict(_edges)


def acquire_counts() -> dict[str, int]:
    return dict(_acquires)


def violations() -> list[tuple[str, str]]:
    """Edge pairs observed in both directions — a real lock-order
    inversion executed at runtime."""
    seen = set(_edges)
    return sorted((a, b) for (a, b) in seen if (b, a) in seen and a < b)


def crosscheck(static_edges) -> dict:
    """Label each static (a, b) edge CONFIRMED or PLAUSIBLE against the
    observed graph; report runtime-only edges and order violations."""
    static = {tuple(e) for e in static_edges}
    observed = set(_edges)
    return {
        "confirmed": sorted(static & observed),
        "plausible": sorted(static - observed),
        "novel": sorted(observed - static),
        "violations": violations(),
    }

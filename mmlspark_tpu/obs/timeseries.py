"""Metric history — the time dimension the one-shot registry lacks.

Every registry read (``/metrics``, ``/slo``, a supervisor poll) is
point-in-time: the adaptive bucket ladder wants the request-size
histogram's TREND, the replica autoscaler wants ``serve.queue_depth``
over the last minute, the supervisor policy wants ``train.host_step_ms``
history — and none of them can get it from a registry that only holds
"now". This module is the history: a periodic sampler persisting the
SLO/autoscale series into

* a bounded in-memory **ring** per series (the query surface the
  in-process actuators read — :meth:`MetricHistory.range`,
  :meth:`~MetricHistory.rate`, :meth:`~MetricHistory.last`), and
* an append-only **JSONL history file** (one ``{"t", "k", "v"}`` line
  per observation; load it back with :meth:`MetricHistory.load` for
  off-process analysis, or ship it with the fleet snapshots — the
  fleet exporter writes it into its own ``proc_*/`` directory).

What gets sampled is prefix-selected (:data:`DEFAULT_PREFIXES` names
exactly the signals ROADMAP items 1/3/4 act on: the ``serve.slo_burn_*``
/ queue-depth / occupancy gauges, ``train.host_step_ms``, and the
``train.service.*`` / ``train.fleet.*`` supervision series); counters
are sampled too so :meth:`~MetricHistory.rate` turns them into per-
second rates. Sampling is registry READS only — the one-substrate rule
holds, and an unsampled history costs nothing.

Enable standalone with :func:`enable` (module-level :func:`range_`,
:func:`rate`, :func:`last` delegate to the active sampler's history),
or implicitly through ``obs.fleet.enable`` / ``MMLSPARK_TPU_FLEET``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable

from mmlspark_tpu.obs.metrics import (
    Counter, Gauge, format_series,
)

SAMPLER_THREAD = "TimeSeriesSampler"

#: the SLO/autoscale/supervision series the default sampler persists —
#: the signals the adaptive ladder, the replica autoscaler, and the
#: supervisor policy consume (docs/observability.md §timeseries)
DEFAULT_PREFIXES = (
    "serve.slo_burn_",
    "serve.slo_budget_remaining",
    "serve.queue_depth",
    "serve.occupancy_mean_window",
    "serve.replica_skew",
    "serve.ttft_",
    "serve.itl_",
    "serve.lane_",
    "serve.fleet.",
    "train.host_step_ms",
    "train.host_skew",
    "train.service.",
    "train.fleet.",
)


def _series_name(key: str) -> str:
    """``name{labels}`` → ``name`` (the metric-name part of a key)."""
    brace = key.find("{")
    return key if brace < 0 else key[:brace]


class MetricHistory:
    """Bounded per-series ring of ``(t, value)`` observations plus an
    optional append-only JSONL sink. Thread-safe (the sampler thread
    appends while actuators query)."""

    def __init__(self, maxlen: int = 4096, path: str | None = None):
        self.maxlen = int(maxlen)
        self.path = path
        self._lock = threading.Lock()
        self._series: dict[str, deque] = {}
        self._fh = open(path, "a", encoding="utf-8") if path else None

    # -- writes --

    def append(self, t: float, key: str, value: float) -> None:
        line = None
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                ring = self._series[key] = deque(maxlen=self.maxlen)
            ring.append((float(t), float(value)))
            if self._fh is not None:
                line = json.dumps({"t": round(float(t), 6), "k": key,
                                   "v": float(value)})
                self._fh.write(line + "\n")

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- queries (the actuator surface) --

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def range(self, name: str, t0: float | None = None,
              t1: float | None = None) -> dict[str, list[tuple]]:
        """Observations for every series whose metric NAME equals
        ``name`` (or whose full ``name{labels}`` key equals it),
        bounded to ``[t0, t1]`` when given: ``{series_key: [(t, v),
        ...]}``, oldest first. The shape downstream consumers want —
        one fleet often holds the same gauge under several label sets
        (per model, per host)."""
        with self._lock:
            items = [(k, list(ring)) for k, ring in self._series.items()
                     if k == name or _series_name(k) == name]
        out: dict[str, list[tuple]] = {}
        for k, samples in items:
            kept = [(t, v) for t, v in samples
                    if (t0 is None or t >= t0)
                    and (t1 is None or t <= t1)]
            if kept:
                out[k] = kept
        return out

    def last(self, name: str, n: int = 1) -> dict[str, list[tuple]]:
        """The newest ``n`` observations per matching series."""
        return {k: samples[-n:]
                for k, samples in self.range(name).items()}

    def rate(self, name: str,
             window_s: float | None = None) -> dict[str, float]:
        """Per-second first-difference rate over the window (or the
        whole ring): ``(v_last - v_first) / (t_last - t_first)`` —
        turns a sampled cumulative counter into a rate; series with
        fewer than two samples (or zero elapsed time) are omitted.
        The window is anchored at each series' NEWEST sample, not at
        ``time.time()`` — sample timestamps come from the sampler's
        (possibly injected) clock, and a history loaded from an
        archived JSONL would otherwise fall entirely outside a
        wall-clock window and silently rate to nothing."""
        out: dict[str, float] = {}
        for k, samples in self.range(name).items():
            if window_s is not None and samples:
                t_last = samples[-1][0]
                samples = [(t, v) for t, v in samples
                           if t >= t_last - float(window_s)]
            if len(samples) < 2:
                continue
            (ta, va), (tb, vb) = samples[0], samples[-1]
            if tb <= ta:
                continue
            out[k] = (vb - va) / (tb - ta)
        return out

    # -- persistence --

    @classmethod
    def load(cls, path: str, maxlen: int = 4096) -> "MetricHistory":
        """Rebuild a history from its JSONL file (unparseable lines —
        a torn tail write — are skipped, never fatal)."""
        hist = cls(maxlen=maxlen)
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                try:
                    row = json.loads(line)
                    hist.append(float(row["t"]), str(row["k"]),
                                float(row["v"]))
                except (ValueError, KeyError, TypeError):
                    continue
        return hist


class TimeSeriesSampler:
    """Periodic (or on-demand) sampler: reads the prefix-selected
    gauges/counters of its registries into a :class:`MetricHistory`.

    ``registries`` is a zero-arg callable returning the registries to
    sample each tick (default: the process-wide registry plus every
    ``obs.fleet`` registry source — so per-model serve registries ride
    along); resolving per tick means models added after the sampler
    started are picked up. ``sample()`` may also be called explicitly
    (each ``/slo`` poll can be one history sample, the same on-demand
    discipline as the SLO tracker).
    """

    def __init__(self, registries: Callable[[], list] | None = None,
                 prefixes: tuple = DEFAULT_PREFIXES,
                 interval_s: float = 1.0,
                 path: str | None = None,
                 maxlen: int = 4096,
                 clock: Callable[[], float] = time.time):
        from mmlspark_tpu.obs import fleet as _fleet
        self.registries = registries or _fleet.all_registries
        self.prefixes = tuple(prefixes)
        self.interval_s = float(interval_s)
        self._clock = clock
        self.history = MetricHistory(maxlen=maxlen, path=path)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _match(self, name: str) -> bool:
        return name.startswith(self.prefixes)

    def sample(self, now: float | None = None) -> int:
        """Take one sample of every matching series; returns how many
        observations were recorded."""
        now = self._clock() if now is None else float(now)
        n = 0
        for reg in self.registries():
            for m in reg.iter_metrics():
                if not self._match(m.name):
                    continue
                if isinstance(m, (Gauge, Counter)):
                    v = m.value
                    if v is None:
                        continue
                    self.history.append(
                        now, format_series(m.name, m.labels), float(v))
                    n += 1
        self.history.flush()
        return n

    # -- lifecycle --

    def start(self) -> "TimeSeriesSampler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=SAMPLER_THREAD, daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # pragma: no cover - sampler never dies
                pass

    def close(self) -> None:
        """Stop the cadence thread (joined — no stray threads), take
        one final sample, and close the JSONL sink."""
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        self._thread = None
        try:
            self.sample()
        except Exception:  # pragma: no cover - defensive final sample
            pass
        self.history.close()


# ---------------------------------------------------------------------------
# module surface
# ---------------------------------------------------------------------------

_sampler: TimeSeriesSampler | None = None


def enable(path: str | None = None, **kwargs: Any) -> TimeSeriesSampler:
    """Start the process-wide sampler (replacing a previous one — its
    history is closed first). ``kwargs`` forward to
    :class:`TimeSeriesSampler`."""
    global _sampler
    if _sampler is not None:
        _sampler.close()
    _sampler = TimeSeriesSampler(path=path, **kwargs).start()
    return _sampler


def disable() -> None:
    global _sampler
    if _sampler is not None:
        _sampler.close()
        _sampler = None


def enabled() -> bool:
    return _sampler is not None


def sampler() -> TimeSeriesSampler | None:
    return _sampler


def history() -> MetricHistory | None:
    return _sampler.history if _sampler is not None else None


def range_(name: str, t0: float | None = None,
           t1: float | None = None) -> dict[str, list[tuple]]:
    """Module-level delegate to the active sampler's history (empty
    when no sampler is enabled)."""
    h = history()
    return {} if h is None else h.range(name, t0=t0, t1=t1)


def rate(name: str, window_s: float | None = None) -> dict[str, float]:
    h = history()
    return {} if h is None else h.rate(name, window_s=window_s)


def last(name: str, n: int = 1) -> dict[str, list[tuple]]:
    h = history()
    return {} if h is None else h.last(name, n=n)


# `range` is a builtin; export the query API under the natural name too
# for the documented `timeseries.range()` spelling
range = range_  # noqa: A001 - deliberate module-namespace alias

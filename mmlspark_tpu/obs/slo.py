"""SLO engine — declarative objectives, windowed burn rates, derived
signals. Registry reads only.

The SRE framing (Beyer et al., *Site Reliability Engineering*, 2016):
an SLO is a **latency objective** plus an **error budget** (1 −
objective), and the operational signal is the **burn rate** — how many
times faster than budget the service is consuming its error allowance,
measured over a short window (paging speed) and a long window
(sustained degradation). This module computes all of it *purely from
the existing obs registry*: the ``serve.*`` counters and histograms
:class:`~mmlspark_tpu.serve.stats.ServerStats` already records. No new
side-channel counters — the one-substrate rule of docs/observability.md
holds, and the crossing counters stay bit-for-bit equal to
``plan.count_crossings``.

* :class:`SLOSpec` — the declarative objective (success ratio, latency
  target at a quantile, burn windows + thresholds).
* :class:`SLOTracker` — samples a :class:`ServerStats` registry on
  demand (each ``/slo`` or ``/healthz`` poll is one sample), keeps a
  time-bounded ring of counter snapshots, and computes short/long
  window burn rates from the deltas. It also publishes the **derived
  gauges** downstream consumers need — ``serve.queue_depth`` (the
  replica-autoscaling signal), ``serve.occupancy_mean_window`` and
  ``serve.replica_skew`` (the adaptive-bucket-ladder signals) and the
  burn gauges themselves — back into the same per-model registry, so
  ``/metrics`` exports them like any other series.
* :class:`SlowStepDetector` — the train-loop analog: a rolling-median
  outlier detector over per-step dispatch time (``train.step_ms``
  histogram), flagging steps slower than ``factor ×`` the window median
  as ``train/slow_step`` events + a ``train.slow_steps`` counter.

The health state machine these signals drive lives in
:mod:`mmlspark_tpu.obs.health`.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.lockwitness import named_lock
from mmlspark_tpu.obs.metrics import registry as _registry
from mmlspark_tpu.obs.spans import event as _event


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One service-level objective, declaratively.

    ``objective`` is the success-ratio target over terminal requests
    (completed vs. rejected/expired/timed-out/failed); its complement is
    the error budget. ``latency_ms`` (optional) is the latency objective
    at ``latency_quantile`` over the e2e reservoir. Burn rates are
    evaluated over ``window_s`` (short — the fast-burn page signal) and
    ``long_window_s`` (sustained); ``fast_burn``/``slow_burn`` are the
    multiples of budget-rate at which the health layer calls the model
    unhealthy/degraded. Windows with fewer than ``min_requests``
    terminal requests return no burn verdict (no traffic ≠ no errors).
    """

    name: str = "serve-default"
    objective: float = 0.999
    latency_ms: float | None = None
    latency_quantile: str = "p99"
    window_s: float = 60.0
    long_window_s: float = 300.0
    fast_burn: float = 14.0
    slow_burn: float = 2.0
    min_requests: int = 10

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO objective must be in (0, 1): {self.objective}")
        if self.latency_quantile not in ("p50", "p95", "p99"):
            raise ValueError(
                f"latency_quantile must be p50/p95/p99: "
                f"{self.latency_quantile!r}")
        if self.window_s <= 0 or self.long_window_s < self.window_s:
            raise ValueError(
                f"need 0 < window_s <= long_window_s, got "
                f"{self.window_s}/{self.long_window_s}")
        if self.min_requests < 1:
            # min_requests is the zero-traffic guard: a window below it
            # returns no verdict instead of dividing by its (possibly
            # zero) terminal count
            raise ValueError(
                f"min_requests must be >= 1: {self.min_requests}")
        if not (self.fast_burn > 0 and self.slow_burn > 0):
            raise ValueError(
                f"burn thresholds must be > 0: fast_burn="
                f"{self.fast_burn}, slow_burn={self.slow_burn}")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @classmethod
    def parse(cls, obj: Any) -> "SLOSpec":
        """None → the default spec; a dict → field overrides; an
        SLOSpec passes through (the ``ServeConfig.slo`` coercion)."""
        if obj is None:
            return cls()
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            return cls(**obj)
        raise TypeError(
            f"slo must be an SLOSpec, a dict of its fields, or None: "
            f"{type(obj).__name__}")

    def describe(self) -> dict:
        return {
            "name": self.name,
            "objective": self.objective,
            "budget": round(self.budget, 9),
            "latency_ms": self.latency_ms,
            "latency_quantile": self.latency_quantile,
            "window_s": self.window_s,
            "long_window_s": self.long_window_s,
            "fast_burn": self.fast_burn,
            "slow_burn": self.slow_burn,
            "min_requests": self.min_requests,
        }


# error-side terminal states, as recorded by ServerStats — the registry
# counter names the tracker reads (never writes)
ERROR_COUNTERS = ("rejected_overload", "expired_deadline", "timed_out",
                  "failed")


class SLOTracker:
    """Windowed burn-rate evaluation over one model's stats registry.

    Sampling is on-demand: every registry read is an atomic
    counter/histogram read of the shared primitives, and the whole
    sample (ring append + window scans) runs under one lock because the
    HTTP front end is a ThreadingHTTPServer — concurrent ``/healthz``
    and ``/slo`` probes hit the same tracker. There is no background
    thread — an unpolled tracker costs nothing.
    """

    __slots__ = ("spec", "stats", "queued_fn", "_clock", "_samples",
                 "_lock")

    def __init__(self, spec: SLOSpec, stats: Any,
                 queued_fn: Callable[[], int] | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.spec = spec
        self.stats = stats              # serve.stats.ServerStats
        self.queued_fn = queued_fn      # live queue depth (admission)
        self._clock = clock
        # (t, reads) snapshots. Bounded by TIME, not a fixed maxlen (a
        # fixed cap silently shrank the long window under frequent
        # polling): samples older than 2x the long window are pruned on
        # append, and appends closer together than long_window_s/4096
        # coalesce into the newest slot, so the ring holds at most
        # ~8192 samples at any poll rate
        self._samples: deque = deque()
        self._lock = named_lock("obs.slo.SLOTracker._lock")

    # -- the one read seam --

    def _read(self) -> dict:
        """Every registry value one sample consumes, read once. This is
        the auditable surface of the 'registry reads only' contract —
        the burn/health math below touches nothing else."""
        s = self.stats
        errors = {name: getattr(s, name) for name in ERROR_COUNTERS}
        return {
            "admitted": s.admitted,
            "completed": s.completed,
            "errors": errors,
            "error_total": sum(errors.values()),
        }

    # -- sampling --

    def _window_delta(self, now: float, cur: dict,
                      window_s: float) -> dict | None:
        """Deltas of the terminal counters against the newest sample at
        least ``window_s`` old (or the oldest held, once the ring spans
        less than the window); None with fewer than two samples."""
        base = None
        for t, reads in self._samples:
            if now - t >= window_s:
                base = reads  # keep scanning: the NEWEST old-enough one
            else:
                break
        if base is None:
            if not self._samples or self._samples[0][1] is cur:
                return None
            base = self._samples[0][1]
        completed = cur["completed"] - base["completed"]
        err = cur["error_total"] - base["error_total"]
        rejected = (cur["errors"]["rejected_overload"]
                    - base["errors"]["rejected_overload"])
        admitted = cur["admitted"] - base["admitted"]
        return {"completed": completed, "errors": err,
                "rejected": rejected, "admitted": admitted,
                "terminal": completed + err}

    def _burn(self, delta: dict | None) -> tuple[float | None, dict]:
        """(burn multiple, window detail) — None burn when the window
        carries too little traffic for a verdict."""
        detail = {"terminal": 0, "errors": 0, "rejected": 0,
                  "admitted": 0, "error_rate": None}
        if delta is None:
            return None, detail
        detail.update({k: delta[k] for k in
                       ("terminal", "errors", "rejected", "admitted")})
        if delta["terminal"] < self.spec.min_requests:
            return None, detail
        rate = delta["errors"] / delta["terminal"]
        detail["error_rate"] = round(rate, 6)
        return rate / self.spec.budget, detail

    def _latency(self) -> tuple[float | None, bool | None]:
        pct = self.stats.e2e_percentiles()
        if pct is None:
            return None, None
        observed = float(pct[self.spec.latency_quantile])
        if self.spec.latency_ms is None:
            return observed, None
        return observed, observed <= self.spec.latency_ms

    def _replica_skew(self) -> float | None:
        """Load imbalance of the DP fan-out from the per-replica batch
        counters: (max − min) / max over replicas, 0 for perfectly even,
        None when the model doesn't serve replicated."""
        counts = self.stats.replica_batch_counts()
        if len(counts) < 2:
            return None
        hi, lo = max(counts.values()), min(counts.values())
        return 0.0 if hi == 0 else round((hi - lo) / hi, 6)

    def sample(self, now: float | None = None) -> dict:
        """Take one sample: read the registry, update the ring, compute
        burn rates + derived signals, publish the derived gauges into
        the model's registry, and return the JSON-safe status dict."""
        with self._lock:
            return self._sample_locked(now)

    def _sample_locked(self, now: float | None) -> dict:
        spec = self.spec
        now = self._clock() if now is None else float(now)
        cur = self._read()
        # append BEFORE evaluating so a first sample evaluates against
        # itself (no-traffic verdict) instead of crashing; samples
        # arriving within one ring-resolution step of the newest
        # coalesce into it — replacing the READS but keeping the slot's
        # original timestamp (counters are cumulative, so the newer
        # snapshot loses nothing a window spanning >= one step can see;
        # rewriting the timestamp would make the tail a sliding target
        # under sustained sub-resolution polling — it never ages past
        # the step, no base sample ever accumulates, and the burn math
        # returns no verdict forever)
        if self._samples and (now - self._samples[-1][0]
                              < spec.long_window_s / 4096.0):
            self._samples[-1] = (self._samples[-1][0], cur)
        else:
            self._samples.append((now, cur))
        while self._samples and (now - self._samples[0][0]
                                 > spec.long_window_s * 2):
            self._samples.popleft()
        burn_short, short = self._burn(
            self._window_delta(now, cur, spec.window_s))
        burn_long, long_ = self._burn(
            self._window_delta(now, cur, spec.long_window_s))
        latency_ms, latency_ok = self._latency()
        terminal = cur["completed"] + cur["error_total"]
        if terminal:
            consumed = (cur["error_total"] / terminal) / spec.budget
            budget_remaining = round(max(0.0, 1.0 - consumed), 6)
        else:
            budget_remaining = 1.0
        queue_depth = None if self.queued_fn is None \
            else int(self.queued_fn())
        occupancy = self.stats.occupancy_mean()
        skew = self._replica_skew()
        ttft = self.stats.ttft_percentiles()
        itl = self.stats.itl_percentiles()
        self._publish_gauges(burn_short, burn_long, queue_depth,
                             occupancy, skew, budget_remaining,
                             ttft=ttft, itl=itl)
        return {
            "slo": spec.describe(),
            "burn_rate_short": None if burn_short is None
            else round(burn_short, 4),
            "burn_rate_long": None if burn_long is None
            else round(burn_long, 4),
            "window_short": short,
            "window_long": long_,
            "latency_ms": latency_ms,
            "latency_ok": latency_ok,
            "budget_remaining": budget_remaining,
            "queue_depth": queue_depth,
            "occupancy_mean": occupancy,
            "replica_skew": skew,
            # per-token SLOs (token serving only; None for batch models)
            "ttft_ms": None if ttft is None else {
                k: round(v, 3) for k, v in ttft.items()},
            "itl_ms": None if itl is None else {
                k: round(v, 3) for k, v in itl.items()},
            "counters": {"admitted": cur["admitted"],
                         "completed": cur["completed"],
                         **cur["errors"]},
            "min_requests": spec.min_requests,
        }

    def _publish_gauges(self, burn_short, burn_long, queue_depth,
                        occupancy, skew, budget_remaining,
                        ttft=None, itl=None) -> None:
        """Derived values become first-class gauges in the model's own
        registry — the queue-depth/skew/burn series autoscalers and the
        adaptive ladder consume from /metrics without re-deriving."""
        reg = self.stats.registry
        lbl = self.stats.labels
        # a no-verdict window resets the burn gauges to 0 — freezing
        # them at the last incident-era value would keep alerts (and
        # the autoscaler) firing long after traffic stopped, while /slo
        # simultaneously reports no verdict
        reg.gauge("serve.slo_burn_short",
                  **lbl).set(burn_short if burn_short is not None else 0.0)
        reg.gauge("serve.slo_burn_long",
                  **lbl).set(burn_long if burn_long is not None else 0.0)
        reg.gauge("serve.slo_budget_remaining",
                  **lbl).set(budget_remaining)
        if queue_depth is not None:
            reg.gauge("serve.queue_depth", **lbl).set(queue_depth)
        if occupancy is not None:
            reg.gauge("serve.occupancy_mean_window",
                      **lbl).set(occupancy)
        if skew is not None:
            reg.gauge("serve.replica_skew", **lbl).set(skew)
        # per-token SLO gauges (token serving): the TimeSeriesSampler
        # persists serve.ttft_*/serve.itl_* into MetricHistory, so the
        # streaming latency objectives get the same history/rate surface
        # as the burn gauges
        if ttft is not None:
            reg.gauge("serve.ttft_p50_ms", **lbl).set(ttft["p50"])
            reg.gauge("serve.ttft_p99_ms", **lbl).set(ttft["p99"])
        if itl is not None:
            reg.gauge("serve.itl_p99_ms", **lbl).set(itl["p99"])


class SlowStepDetector:
    """Rolling-median outlier detection for the train step loop.

    ``observe(dur_ms)`` records every step's dispatch time into a
    windowed ``train.step_ms`` histogram (the process-wide registry) and
    flags a step slower than ``factor ×`` the median of the PRIOR
    window — after ``min_samples`` steps have established a baseline —
    as one ``train/slow_step`` event plus a ``train.slow_steps``
    counter increment. The baseline is the window median, recomputed
    every ``window // 4`` observations (a per-step copy + sort of the
    full window would cost host time comparable to the sub-ms dispatch
    it measures), so a genuine regime change (bigger batches after a
    rescale) re-baselines itself within one window instead of flagging
    forever. Call sites gate on ``obs.runtime._enabled``; the detector
    assumes it only runs enabled.
    """

    __slots__ = ("factor", "min_samples", "_hist", "_counter", "_labels",
                 "_window", "_count", "_every", "_baseline",
                 "_baseline_at")

    def __init__(self, loop: str = "train", factor: float = 4.0,
                 min_samples: int = 16, window: int = 512):
        self.factor = float(factor)
        self.min_samples = int(min_samples)
        reg = _registry()
        self._labels = {"loop": loop}
        self._hist = reg.histogram("train.step_ms", window=window,
                                   **self._labels)
        self._counter = reg.counter("train.slow_steps", **self._labels)
        # the baseline window is PER DETECTOR, not the interned registry
        # histogram: a second fit in the same process gets the same
        # train.step_ms{loop=...} series (interned by (name, labels)),
        # and baselining a fresh fit against the previous fit's step
        # times would flag every step of a legitimately slower run
        self._window: deque = deque(maxlen=int(window))
        self._count = 0
        self._every = max(1, int(window) // 4)
        self._baseline: float | None = None
        self._baseline_at = 0

    def observe(self, dur_ms: float) -> bool:
        """Record one step; True when it was flagged slow."""
        prior_count = self._count
        if prior_count >= self.min_samples and (
                self._baseline is None
                or prior_count - self._baseline_at >= self._every):
            # median of the window BEFORE this observation lands
            self._baseline = float(np.median(self._window))
            self._baseline_at = prior_count
        self._hist.observe(dur_ms)
        self._window.append(dur_ms)
        self._count = prior_count + 1
        if prior_count < self.min_samples:
            return False
        baseline = self._baseline
        if baseline is None or baseline <= 0 \
                or dur_ms <= self.factor * baseline:
            return False
        self._counter.add()
        if _rt._enabled:
            _event("train/slow_step", "train",
                   {**self._labels, "step_ms": round(dur_ms, 3),
                    "median_ms": round(baseline, 3),
                    "factor": round(dur_ms / baseline, 2)})
        return True

"""Trace records — the data the span tracer writes and exporters read.

Plain slotted records (no dataclass machinery on the hot path) holding
wall timestamps in integer nanoseconds (``time.perf_counter_ns`` epoch —
monotonic, comparable across threads of one process) plus the thread
identity Chrome-trace lanes group by.
"""

from __future__ import annotations

from typing import Any


class SpanRecord:
    """One completed span: a named, labeled interval on one thread.

    ``trace`` and ``links`` are the request-scoped tracing fields
    (``obs/context.py``): ``trace`` is the request trace id the span was
    recorded under (inherited from the thread's active request context),
    and ``links`` is the tuple of OTHER trace ids a fan-in/fan-out span
    touches (a bucket-batch span links every coalesced request's trace).
    Both default to None so nesting/threading stay unchanged for spans
    recorded outside any request.
    """

    __slots__ = ("name", "cat", "start_ns", "dur_ns", "tid", "thread_name",
                 "span_id", "parent_id", "depth", "labels", "trace",
                 "links")

    def __init__(self, name: str, cat: str, start_ns: int, dur_ns: int,
                 tid: int, thread_name: str, span_id: int,
                 parent_id: int | None, depth: int,
                 labels: dict | None, trace: int | None = None,
                 links: tuple | None = None):
        self.name = name
        self.cat = cat
        self.start_ns = start_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.thread_name = thread_name
        self.span_id = span_id
        self.parent_id = parent_id
        self.depth = depth
        self.labels = labels
        self.trace = trace
        self.links = links

    @property
    def end_ns(self) -> int:
        return self.start_ns + self.dur_ns

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "cat": self.cat,
            "start_ns": self.start_ns, "dur_ns": self.dur_ns,
            "tid": self.tid, "thread_name": self.thread_name,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "depth": self.depth, "labels": self.labels or {},
            "trace": self.trace,
            "links": list(self.links) if self.links else [],
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"SpanRecord({self.name!r}, cat={self.cat!r}, "
                f"dur={self.dur_ns / 1e6:.3f}ms, depth={self.depth})")


class EventRecord:
    """One instant event (a point, not an interval) on one thread."""

    __slots__ = ("name", "cat", "ts_ns", "tid", "thread_name", "labels")

    def __init__(self, name: str, cat: str, ts_ns: int, tid: int,
                 thread_name: str, labels: dict | None):
        self.name = name
        self.cat = cat
        self.ts_ns = ts_ns
        self.tid = tid
        self.thread_name = thread_name
        self.labels = labels

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "cat": self.cat, "ts_ns": self.ts_ns,
            "tid": self.tid, "thread_name": self.thread_name,
            "labels": self.labels or {},
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"EventRecord({self.name!r}, cat={self.cat!r})"

"""Fleet telemetry plane — cross-process aggregation and clock-aligned
fleet timelines.

Every observability surface before this module is single-process: the
registry, the span ring, the flight recorder, the SLO tracker all live
and die inside one interpreter. A supervised training job (PR 11) spans
N worker processes; a sharded serve fleet (PR 7/13) spans replica lanes
across hosts — and the signals ROADMAP's actuators want (adaptive
ladder, supervisor policy, burn-driven autoscaling) are FLEET signals.
This module is the Dapper/Monarch step: per-process registries and span
rings become one merged, queryable, clock-aligned plane, with a shared
filesystem as the transport (the same contract as checkpoints and the
service beacons — workers and collectors share no memory).

* :class:`TelemetryExporter` — each process writes **atomic delta
  snapshots** of its metric registries + the span-ring tail to
  ``<fleet_dir>/proc_<host>_<pid>/snap_NNNNNN.json`` on a watchdog-like
  cadence and at exit/crash (temp file + ``os.replace``; bounded
  retention). Counters are cumulative, so the newest snapshot per
  process is the registry truth and retention loses nothing; the ring
  tail is the delta part (the collector dedups by span id). Every
  snapshot carries a paired ``(time.time, perf_counter_ns)`` **stamp**
  so a collector can place perf-clock span timestamps on the wall
  clock, per process.
* :class:`FleetCollector` — merges the snapshots into **fleet
  registries**: counters summed across processes (bit-equal to the sum
  of the per-process registries), gauges kept per process under
  ``host=``/``pid=`` labels (last-written per host wins within one
  process), windowed histograms merged (windows concatenated,
  lifetime count/sum summed). Its :meth:`FleetView.chrome_trace`
  renders one Perfetto timeline for the whole fleet: one process group
  per host, timestamps **skew-aligned** (stamp pairs put each process
  on its own wall clock; the fenced-collective seams — the train
  liveness allgather's ``train/liveness_sync`` span and the serve
  lockstep ``serve/lockstep_agree`` span, which END at the same real
  instant on every participating process — correct residual wall-clock
  skew between hosts), and **cross-process flows stitched** at those
  fence seams so the barrier structure draws as arrows across process
  groups.

Enable with ``MMLSPARK_TPU_FLEET=<dir>`` (read once at import through
``core.config`` — the PR 9 env-sibling precedence: explicit
``enable()``/``disable()`` calls override the env) or
``obs.fleet.enable(dir)``. Enabling also starts a
:mod:`~mmlspark_tpu.obs.timeseries` sampler persisting the SLO/
autoscale gauges to ``<proc_dir>/timeseries.jsonl``. Disabled (the
default) the only cost anywhere is one module-attribute check (the
flight recorder's dump hook reads ``_exp``); there are no per-seam
calls — the exporter drives itself.

Surfaces: ``tools/fleet.py`` (status / metrics / trace / watch), the
serve ``/fleet`` endpoint (JSON + Prometheus via the existing
negotiation), and :class:`~mmlspark_tpu.train.service.TrainSupervisor`
publishing ``train.fleet.*`` aggregates from the worker beacons. See
docs/observability.md §fleet telemetry plane.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import socket
import threading
import time
import weakref
from typing import Any, Callable, Iterable

import numpy as np

from mmlspark_tpu.core import config
from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, registry as _registry,
)

FLEET_VERSION = 1
DEFAULT_INTERVAL_S = 1.0
DEFAULT_RING_TAIL = 4096
DEFAULT_RETENTION = 8

EXPORTER_THREAD = "FleetExporter"

#: span names recorded at the fenced cross-process seams — every
#: participating process exits the underlying collective at the same
#: real instant, which is what makes these spans both the skew
#: CORRECTION anchor and the cross-process flow STITCH points
# lifecycle/publish_fence is the train→deployment-plane handoff: the
# worker brackets its result write, the supervisor's Publisher brackets
# its read+gate+publish (mmlspark_tpu/lifecycle/publish.py)
FENCE_SPAN_NAMES = ("train/liveness_sync", "serve/lockstep_agree",
                    "lifecycle/publish_fence")

_PROC_DIR_RE = re.compile(r"^proc_(?P<host>.+)_(?P<pid>\d+)$")
_SNAP_RE = re.compile(r"^snap_(?P<seq>\d{6})\.json$")


# ---------------------------------------------------------------------------
# registry sources — which registries a process exports (and the
# timeseries sampler samples) beyond the process-wide default
# ---------------------------------------------------------------------------

# callables returning a list of MetricsRegistry; the serve ModelServer
# registers its per-model stats registries here so fleet snapshots (and
# the timeseries history) carry the serve.* series too. Bound methods
# are held WEAKLY: a ModelServer abandoned without close() (e.g. after
# a failed add_model) must not be pinned alive — and kept exporting its
# dead series — by the module-global source list for the process
# lifetime. Plain callables are held strongly (they own no big state).
_sources: list = []  # weakref.WeakMethod | callable
_sources_lock = threading.Lock()


def _resolve_source(entry: Any) -> Callable[[], list] | None:
    if isinstance(entry, weakref.WeakMethod):
        return entry()  # None once the bound object was collected
    return entry


def add_registry_source(fn: Callable[[], list]) -> None:
    """Register a callable returning extra :class:`MetricsRegistry`
    instances to export/sample alongside the process-wide registry
    (idempotent; bound methods are referenced weakly — see above)."""
    with _sources_lock:
        if any(_resolve_source(e) == fn for e in _sources):
            return
        try:
            _sources.append(weakref.WeakMethod(fn))
        except TypeError:  # not a bound method
            _sources.append(fn)


def remove_registry_source(fn: Callable[[], list]) -> None:
    with _sources_lock:
        _sources[:] = [e for e in _sources
                       if _resolve_source(e) is not None
                       and _resolve_source(e) != fn]


def all_registries() -> list:
    """The process-wide registry plus every registered source's
    registries. Dead entries — a collected bound-method owner, or a
    source that raises — are dropped/skipped, never fatal: telemetry
    must not take down the process it reports on."""
    regs = [_registry()]
    fns = []
    with _sources_lock:
        live = []
        for e in _sources:
            f = _resolve_source(e)
            if f is not None:
                live.append(e)
                fns.append(f)
        _sources[:] = live
    for fn in fns:
        try:
            regs.extend(fn())
        except Exception:  # pragma: no cover - defensive
            pass
    return regs


# ---------------------------------------------------------------------------
# the snapshot format
# ---------------------------------------------------------------------------


def _dump_registries(regs: Iterable) -> list[dict]:
    """Structured dump of every metric: ``{"kind", "name", "labels",
    ...}`` rows (NOT the human ``name{k=v}`` snapshot keys — the
    collector merges by (name, labels) and string keys would need
    un-parsing). Histograms carry their raw WINDOW so fleet percentiles
    can be computed over the merged windows, plus the exact lifetime
    count/sum."""
    rows: list[dict] = []
    for reg in regs:
        for m in reg.iter_metrics():
            row: dict[str, Any] = {"name": m.name,
                                   "labels": [list(kv) for kv in m.labels]}
            if isinstance(m, Counter):
                row["kind"] = "counter"
                row["value"] = m.value
            elif isinstance(m, Gauge):
                v = m.value
                if v is None:
                    continue  # an unset gauge has no fleet value
                row["kind"] = "gauge"
                row["value"] = v
            elif isinstance(m, Histogram):
                row["kind"] = "histogram"
                row["count"] = m.count
                row["sum"] = m.sum
                row["window"] = m.values()
            else:  # pragma: no cover - unknown metric kind
                continue
            rows.append(row)
    return rows


def _scrub(obj: Any) -> Any:
    """Non-finite floats → string names (same rule as flight dumps:
    bare NaN/Infinity tokens are not valid JSON for strict consumers)."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj in (float("inf"), float("-inf")):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: _scrub(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_scrub(v) for v in obj]
    return obj


class TelemetryExporter:
    """One process's fleet publisher: periodic + final atomic snapshots
    of its registries and span-ring tail into its own
    ``proc_<host>_<pid>/`` directory."""

    def __init__(self, fleet_dir: str, interval_s: float = DEFAULT_INTERVAL_S,
                 ring_tail: int = DEFAULT_RING_TAIL,
                 retention: int = DEFAULT_RETENTION,
                 host: str | None = None):
        self.fleet_dir = str(fleet_dir)
        self.interval_s = float(interval_s)
        self.ring_tail = int(ring_tail)
        self.retention = max(int(retention), 1)
        self.host = host or socket.gethostname()
        self.pid = os.getpid()
        self.proc_dir = os.path.join(
            self.fleet_dir, f"proc_{self.host}_{self.pid}")
        os.makedirs(self.proc_dir, exist_ok=True)
        self._lock = threading.Lock()
        # resume seq past any snapshots already in the proc dir (a
        # disable()/enable() cycle, or a reconfigure): restarting at 0
        # would make the name-sorted retention sweep prune the FRESH
        # snapshots while keeping the stale ones as "newest truth"
        existing = [int(m.group("seq")) for m in
                    (_SNAP_RE.match(n) for n in os.listdir(self.proc_dir))
                    if m]
        self._seq = max(existing, default=0)
        self._closed = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name=EXPORTER_THREAD, daemon=True)
        self._thread.start()

    # -- the snapshot --

    def snapshot(self, reason: str = "interval",
                 extra: dict | None = None) -> str | None:
        """Write one snapshot; returns its path (None once closed or on
        an unwritable directory — telemetry export never raises into
        the process it observes). Concurrency-safe: the seq counter and
        the retention sweep run under one lock, so the watchdog-cadence
        thread and an explicit exit/crash snapshot never tear."""
        with self._lock:
            if self._closed and reason == "interval":
                return None
            self._seq += 1
            seq = self._seq
            payload: dict[str, Any] = {
                "fleet": FLEET_VERSION,
                "host": self.host,
                "pid": self.pid,
                "seq": seq,
                "reason": reason,
                # the paired clock stamp: wall and perf read back to
                # back, so `wall_s * 1e9 - perf_ns` is this process's
                # perf→wall offset (the skew model's per-process leg)
                "stamp": {"wall_s": time.time(),
                          "perf_ns": time.perf_counter_ns()},
                "registry": _dump_registries(all_registries()),
                "ring": [r.to_dict() for r in _rt.spans()[-self.ring_tail:]],
            }
            if extra:
                payload["extra"] = extra
            path = os.path.join(self.proc_dir, f"snap_{seq:06d}.json")
            tmp = f"{path}.tmp-{self.pid}"
            try:
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(_scrub(payload), fh)
                os.replace(tmp, path)
            except OSError:  # pragma: no cover - fleet dir vanished
                return None
            self._prune_locked()
            return path

    def _prune_locked(self) -> None:
        """Bounded retention: keep the newest ``retention`` snapshots.
        Counters/gauges lose nothing (the newest snapshot is cumulative
        truth); only ring-tail history older than the retained window
        ages out — the same bounded-forensics tradeoff as the flight
        recorder's dump budget."""
        try:
            snaps = sorted(n for n in os.listdir(self.proc_dir)
                           if _SNAP_RE.match(n))
        except OSError:  # pragma: no cover - dir vanished
            return
        for name in snaps[:-self.retention]:
            try:
                os.remove(os.path.join(self.proc_dir, name))
            except OSError:  # pragma: no cover - concurrent cleanup
                pass

    # -- lifecycle --

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.snapshot("interval")
            except Exception:  # pragma: no cover - exporter never dies
                pass

    def close(self, reason: str = "exit") -> None:
        """Stop the cadence thread (joined — no stray threads) and write
        the final snapshot so a clean exit leaves current truth."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        path = self.snapshot(reason)
        with self._lock:
            self._closed = True
        return path


# ---------------------------------------------------------------------------
# module surface (one attribute `_exp` — the flight hook's only cost)
# ---------------------------------------------------------------------------

_exp: TelemetryExporter | None = None
_atexit_installed = False


def enable(fleet_dir: str | None = None,
           **kwargs: Any) -> TelemetryExporter:
    """Start the fleet exporter (idempotent for the same directory with
    the same kwargs, like ``obs.flight.enable`` — an ensure-on call must
    not reset the seq counter or churn the thread). Also enables the obs
    tracer (the ring it exports is the span buffer) and starts a
    :mod:`~mmlspark_tpu.obs.timeseries` sampler persisting the SLO/
    autoscale gauge history to ``<proc_dir>/timeseries.jsonl`` on the
    same cadence. ``kwargs`` forward to :class:`TelemetryExporter`
    (``interval_s``, ``ring_tail``, ``retention``, ``host``)."""
    global _exp, _atexit_installed
    fleet_dir = fleet_dir or config.get("fleet") or "./fleet"
    if _exp is not None:
        if _exp.fleet_dir == str(fleet_dir) and (
                not kwargs or kwargs == _exp._init_kwargs):
            return _exp
        disable()
    if not _rt._enabled:  # keep a custom buffer_size if already enabled
        _rt.enable()
    exp = TelemetryExporter(fleet_dir, **kwargs)
    exp._init_kwargs = dict(kwargs)
    _exp = exp
    if not _atexit_installed:
        atexit.register(_atexit_close)
        _atexit_installed = True
    from mmlspark_tpu.obs import timeseries as _ts
    _ts.enable(path=os.path.join(exp.proc_dir, "timeseries.jsonl"),
               interval_s=exp.interval_s)
    return exp


def disable() -> None:
    """Stop the exporter (writes its final exit snapshot) and the
    timeseries sampler it started. Does NOT disable the obs tracer."""
    global _exp
    if _exp is not None:
        _exp.close("exit")
        _exp = None
        from mmlspark_tpu.obs import timeseries as _ts
        _ts.disable()


def enabled() -> bool:
    return _exp is not None


def exporter() -> TelemetryExporter | None:
    return _exp


def fleet_dir() -> str | None:
    """The active fleet directory: the live exporter's, else the
    configured (``MMLSPARK_TPU_FLEET``/``config.set("fleet")``) one,
    else None — what the serve ``/fleet`` endpoint and the CLI read."""
    if _exp is not None:
        return _exp.fleet_dir
    d = config.get("fleet")
    return str(d) if d else None


def _atexit_close() -> None:  # pragma: no cover - interpreter exit
    if _exp is not None:
        try:
            _exp.close("exit")
        except Exception:
            pass


def on_flight_dump(reason: str, dump_path: str | None) -> str | None:
    """The flight recorder's crash/hang/signal hook: AFTER its dump is
    on disk, flush one fleet snapshot naming it — pinned order, so the
    fleet plane's last word about a dead process both exists (the
    watchdog-cadence snapshot may be a full interval stale at a crash)
    and points at the richer local forensics. One attribute check when
    the exporter is off."""
    if _exp is None:
        return None
    return _exp.snapshot(reason=f"flight_{reason}",
                         extra={"flight_dump": dump_path})


# ---------------------------------------------------------------------------
# the collector
# ---------------------------------------------------------------------------


class FleetReadError(Exception):
    """A fleet directory is missing or holds no readable snapshots."""


class ProcessTelemetry:
    """Everything collected about one process: its newest registry dump,
    its deduped ring records, and its clock stamp."""

    __slots__ = ("name", "host", "pid", "seq", "reason", "stamp",
                 "registry_rows", "records", "skew_ms")

    def __init__(self, name: str, host: str, pid: int):
        self.name = name
        self.host = host
        self.pid = pid
        self.seq = 0
        self.reason = ""
        self.stamp: dict | None = None
        self.registry_rows: list[dict] = []
        self.records: list[dict] = []
        self.skew_ms: float = 0.0  # fence-seam correction, filled in merge

    def wall_offset_ns(self) -> float | None:
        """perf-clock → this process's OWN wall clock, from the stamp
        pair; None when the process never exported a stamp (a hand-built
        or truncated snapshot — the mixed-clock case the trace renderer
        diagnoses)."""
        if not self.stamp:
            return None
        try:
            return (float(self.stamp["wall_s"]) * 1e9
                    - float(self.stamp["perf_ns"]))
        except (KeyError, TypeError, ValueError):
            return None

    def describe(self) -> dict:
        return {
            "process": self.name, "host": self.host, "pid": self.pid,
            "seq": self.seq, "reason": self.reason,
            "records": len(self.records),
            "series": len(self.registry_rows),
            "stamp_wall_s": (self.stamp or {}).get("wall_s"),
            "skew_correction_ms": round(self.skew_ms, 3),
        }


class FleetView:
    """One collected, merged view of the fleet: the merged registry, the
    per-process telemetry, and the clock-aligned timeline export."""

    def __init__(self, processes: list[ProcessTelemetry]):
        self.processes = processes
        self.registry = MetricsRegistry()
        self._merge_registries()
        if any(p.records for p in self.processes):
            self._align_clocks()

    # -- registry merge --

    def _merge_registries(self) -> None:
        """counters summed; gauges per process under host=/pid= labels
        (each process contributes its last-written value — within one
        host the processes stay distinguishable); histogram windows
        concatenated with exact count/sum summed."""
        # histograms accumulate FIRST, then intern: the fleet window
        # must be sized to the whole concatenation — interning with the
        # default window would truncate N processes' windows to the
        # last 4096 values in directory order, biasing fleet quantiles
        # toward whichever process merged last
        hists: dict[tuple, list] = {}  # (name, lkey) -> [count, sum, values]
        for p in self.processes:
            for row in p.registry_rows:
                labels = {str(k): v for k, v in row.get("labels", ())}
                kind = row.get("kind")
                name = row.get("name")
                if not name:
                    continue
                if kind == "counter":
                    self.registry.counter(name, **labels).add(
                        float(row.get("value", 0.0)))
                elif kind == "gauge":
                    # a series already labeled host= (train.host_step_ms)
                    # keeps its own attribution; pid= always lands, so
                    # two processes on one host stay distinguishable
                    glabels = dict(labels)
                    glabels.setdefault("host", p.host)
                    glabels["pid"] = p.pid
                    self.registry.gauge(
                        name, **glabels).set(float(row.get("value", 0.0)))
                elif kind == "histogram":
                    key = (name, tuple(sorted(labels.items())))
                    slot = hists.setdefault(key, [0, 0.0, []])
                    slot[0] += int(row.get("count", 0))
                    slot[1] += float(row.get("sum", 0.0))
                    slot[2].extend(float(v)
                                   for v in row.get("window", ()))
        for (name, lkey), (count, total, values) in hists.items():
            h = self.registry.histogram(name, window=max(len(values), 1),
                                        **dict(lkey))
            with h._lock:
                h._count += count
                h._sum += total
                h._values.extend(values)

    # -- clock alignment --

    def _fence_ends(self, p: ProcessTelemetry) -> dict[str, list[float]]:
        """Per fence NAME, this process's fence-span end times on its
        own wall clock, in time order. Keyed by name because only
        same-name fences are the same collective — a train worker's
        liveness allgather must never be matched against a serve
        process's lockstep exchange."""
        off = p.wall_offset_ns()
        if off is None:
            return {}
        out: dict[str, list[float]] = {}
        for r in p.records:
            name = r.get("name")
            if name in FENCE_SPAN_NAMES and "start_ns" in r:
                out.setdefault(name, []).append(
                    float(r.get("start_ns", 0))
                    + float(r.get("dur_ns", 0)) + off)
        for ends in out.values():
            ends.sort()
        return out

    def _align_clocks(self) -> None:
        """Two-leg skew model. Leg 1: each process's stamp pair places
        its perf-clock span timestamps on its OWN wall clock. Leg 2:
        wall clocks themselves skew across hosts (NTP drift), so the
        fence-seam spans — which END at the same real instant on every
        participating process (the underlying collective is a barrier)
        — anchor a per-process residual correction. Matching is per
        fence NAME and aligned from the TAIL: the ring retains the
        newest records, so a process whose early fences aged out (or a
        collector that caught one process a beat later) still pairs
        its last fence with the reference's last fence; the correction
        is the median end-time difference over all matched pairs.
        Processes without fence spans (a lone serve process) keep
        correction 0."""
        ref: ProcessTelemetry | None = None
        ref_fences: dict[str, list[float]] = {}
        fences: dict[str, dict[str, list[float]]] = {}
        for p in sorted(self.processes, key=lambda p: (p.host, p.pid)):
            by_name = self._fence_ends(p)
            if not by_name:
                continue
            fences[p.name] = by_name
            if ref is None:
                ref, ref_fences = p, by_name
        if ref is None:
            return
        for p in self.processes:
            by_name = fences.get(p.name)
            if p is ref or not by_name:
                continue
            deltas = []
            for name, ends in by_name.items():
                refs = ref_fences.get(name)
                if not refs:
                    continue  # a fence type the reference never crossed
                n = min(len(ends), len(refs))
                deltas.extend(refs[-n + k] - ends[-n + k]
                              for k in range(n))
            if deltas:
                p.skew_ms = float(np.median(deltas)) / 1e6

    def unaligned(self) -> list[str]:
        """Processes whose snapshots carry no stamp pair — their records
        cannot be placed on the fleet wall clock."""
        return [p.name for p in self.processes
                if p.records and p.wall_offset_ns() is None]

    # -- reads --

    def snapshot(self) -> dict:
        """JSON-safe merged view — the ``/fleet`` endpoint body."""
        return {
            "fleet": FLEET_VERSION,
            "hosts": sorted({p.host for p in self.processes}),
            "processes": [p.describe() for p in self.processes],
            "metrics": self.registry.snapshot(),
        }

    def counter_value(self, name: str, **labels: Any) -> float | None:
        return self.registry.value(name, **labels)

    # -- the fleet timeline --

    def chrome_trace(self) -> dict:
        """One Chrome-trace/Perfetto JSON for the whole fleet: every
        process's ring records on the skew-corrected wall clock (µs
        since the earliest record — Perfetto is happiest with small
        positive timestamps), one process group per host
        (``process_name``/``process_sort_index`` metadata), thread
        lanes preserved per process, and one stitched flow per fence
        index drawing the barrier across the process groups. A process
        without a stamp pair is EXCLUDED from the events (its clock is
        unplaceable) and named in ``fleetMeta.unaligned`` — the
        renderer turns that into the typed mixed-clock diagnostic."""
        events: list[dict] = []
        # (corrected wall ns, record, process) for every span/event
        placed: list[tuple[float, dict, ProcessTelemetry]] = []
        for p in self.processes:
            off = p.wall_offset_ns()
            if off is None:
                continue
            corr = off + p.skew_ms * 1e6
            for r in p.records:
                t = r.get("start_ns", r.get("ts_ns"))
                if not isinstance(t, (int, float)):
                    continue
                placed.append((float(t) + corr, r, p))
        if not placed:
            return {"traceEvents": [], "displayTimeUnit": "ms",
                    "fleetMeta": self._meta()}
        t0 = min(t for t, _r, _p in placed)
        hosts = sorted({p.host for p in self.processes})
        thread_names: dict[tuple[int, int], str] = {}
        # fence name -> pid -> that process's fence spans in time order
        fence_spans: dict[str, dict[int, list[tuple[float, int]]]] = {}
        for t, r, p in sorted(placed, key=lambda x: x[0]):
            tid = int(r.get("tid", 0) or 0)
            thread_names.setdefault(
                (p.pid, tid), str(r.get("thread_name", f"thread-{tid}")))
            args = {k: v for k, v in (r.get("labels") or {}).items()}
            args["host"] = p.host
            if "dur_ns" in r:  # a span
                dur_us = float(r.get("dur_ns", 0)) / 1e3
                events.append({
                    "name": r.get("name", "?"), "cat": r.get("cat", "host"),
                    "ph": "X", "ts": (t - t0) / 1e3, "dur": dur_us,
                    "pid": p.pid, "tid": tid, "args": args,
                })
                name = r.get("name")
                if name in FENCE_SPAN_NAMES:
                    fence_spans.setdefault(name, {}).setdefault(
                        p.pid, []).append(
                        ((t - t0) / 1e3 + dur_us / 2, tid))
            else:  # an instant event
                events.append({
                    "name": r.get("name", "?"), "cat": r.get("cat", "host"),
                    "ph": "i", "s": "t", "ts": (t - t0) / 1e3,
                    "pid": p.pid, "tid": tid, "args": args,
                })
        # stitched cross-process flows: one arrow chain per fence
        # OCCURRENCE that >=2 processes participated in. Matching
        # mirrors _align_clocks: per fence NAME (only same-name fences
        # are the same collective), indexed from the TAIL (ring
        # retention keeps the newest spans, so the last fences of every
        # process are the ones that correspond)
        stitched = 0
        flow_id = 0x66000000
        for name in sorted(fence_spans):
            per_pid = fence_spans[name]
            depth = max(len(v) for v in per_pid.values())
            for j in range(depth):  # j = distance from the tail
                touched = sorted(
                    (spans[len(spans) - 1 - j][0], pid,
                     spans[len(spans) - 1 - j][1])
                    for pid, spans in per_pid.items()
                    if len(spans) > j)
                flow_id += 1
                if len({pid for _ts_, pid, _tid in touched}) < 2:
                    continue
                stitched += 1
                last = len(touched) - 1
                for i, (mid_us, pid, tid) in enumerate(touched):
                    events.append({
                        "name": "fleet-fence", "cat": "fleet.fence",
                        "ph": "s" if i == 0 else
                              ("f" if i == last else "t"),
                        "id": flow_id, "bp": "e",
                        "ts": mid_us, "pid": pid, "tid": tid,
                    })
        for p in self.processes:
            if p.wall_offset_ns() is None:
                continue
            events.append({
                "name": "process_name", "ph": "M", "pid": p.pid,
                "args": {"name": f"{p.host} pid={p.pid}"},
            })
            events.append({
                "name": "process_sort_index", "ph": "M", "pid": p.pid,
                "args": {"sort_index": hosts.index(p.host)},
            })
        for (pid, tid), tname in thread_names.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "fleetMeta": self._meta(stitched_flows=stitched)}

    def _meta(self, stitched_flows: int = 0) -> dict:
        return {
            "fleet": FLEET_VERSION,
            "hosts": {h: sorted(p.pid for p in self.processes
                                if p.host == h)
                      for h in sorted({p.host for p in self.processes})},
            "processes": [p.describe() for p in self.processes],
            "stitched_flows": stitched_flows,
            "unaligned": self.unaligned(),
        }

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.chrome_trace(), fh)
        return path


class FleetCollector:
    """Scan one fleet directory and merge its process snapshots."""

    def __init__(self, fleet_dir: str):
        self.fleet_dir = str(fleet_dir)

    def _proc_dirs(self) -> list[tuple[str, str, int]]:
        try:
            names = sorted(os.listdir(self.fleet_dir))
        except OSError as e:
            raise FleetReadError(
                f"cannot read fleet dir {self.fleet_dir!r}: "
                f"{e.strerror or e}") from e
        out = []
        for name in names:
            m = _PROC_DIR_RE.match(name)
            if m and os.path.isdir(os.path.join(self.fleet_dir, name)):
                out.append((name, m.group("host"), int(m.group("pid"))))
        return out

    def _load_process(self, name: str, host: str, pid: int,
                      include_ring: bool = True,
                      ) -> ProcessTelemetry | None:
        proc = ProcessTelemetry(name, host, pid)
        pdir = os.path.join(self.fleet_dir, name)
        try:
            snaps = sorted(n for n in os.listdir(pdir)
                           if _SNAP_RE.match(n))
        except OSError:
            return None
        seen: set = set()
        loaded_any = False
        if not include_ring:
            # registry-only read: counters/gauges are cumulative, so
            # the NEWEST readable snapshot is the whole truth — walk
            # backward and stop at the first one instead of paying a
            # full-JSON parse (ring arrays included) per retained file
            snaps = list(reversed(snaps))
        for snap in snaps:  # oldest → newest: the last wins the registry
            try:
                with open(os.path.join(pdir, snap),
                          encoding="utf-8") as fh:
                    payload = json.load(fh)
            except (OSError, ValueError):
                continue  # a torn/garbled snapshot never poisons the rest
            if not isinstance(payload, dict):
                continue
            loaded_any = True
            proc.seq = int(payload.get("seq", proc.seq) or 0)
            proc.reason = str(payload.get("reason", ""))
            stamp = payload.get("stamp")
            proc.stamp = stamp if isinstance(stamp, dict) else proc.stamp
            reg = payload.get("registry")
            if isinstance(reg, list):
                proc.registry_rows = reg  # cumulative: newest wins
            if not include_ring:
                break  # newest readable found — nothing older needed
            for r in payload.get("ring") or ():
                if not isinstance(r, dict):
                    continue
                # dedup across overlapping ring tails: span_id is
                # process-unique; instant events key by (tid, ts, name)
                key = (("s", r["span_id"]) if r.get("span_id") is not None
                       else ("e", r.get("tid"), r.get("ts_ns"),
                             r.get("name")))
                if key in seen:
                    continue
                seen.add(key)
                proc.records.append(r)
        return proc if loaded_any else None

    def collect(self, include_ring: bool = True) -> FleetView:
        """Load every process's snapshots and merge. Raises
        :class:`FleetReadError` when the directory is missing or no
        process exported anything readable. ``include_ring=False``
        skips the span-ring parse and the clock alignment entirely —
        the registry-merge-only read the metrics surfaces want: a
        scraper polling ``/fleet`` every few seconds must not pay a
        multi-megabyte ring parse per scrape for a body that only
        serves the merged registry."""
        procs = []
        for name, host, pid in self._proc_dirs():
            p = self._load_process(name, host, pid,
                                   include_ring=include_ring)
            if p is not None:
                procs.append(p)
        if not procs:
            raise FleetReadError(
                f"fleet dir {self.fleet_dir!r} holds no readable "
                "process snapshots (is MMLSPARK_TPU_FLEET pointed at "
                "the right directory, and has any process exported "
                "yet?)")
        return FleetView(procs)

    def status(self) -> dict:
        """Cheap directory-level status (no ring merge): per-process
        newest snapshot, age, seq — the `tools/fleet.py status` body."""
        now = time.time()
        rows = []
        for name, host, pid in self._proc_dirs():
            pdir = os.path.join(self.fleet_dir, name)
            try:
                snaps = sorted(n for n in os.listdir(pdir)
                               if _SNAP_RE.match(n))
            except OSError:
                continue
            if not snaps:
                continue
            newest = os.path.join(pdir, snaps[-1])
            row = {"process": name, "host": host, "pid": pid,
                   "snapshots": len(snaps)}
            try:
                with open(newest, encoding="utf-8") as fh:
                    payload = json.load(fh)
                row["seq"] = payload.get("seq")
                row["reason"] = payload.get("reason")
                stamp = payload.get("stamp") or {}
                wall = stamp.get("wall_s")
                if isinstance(wall, (int, float)):
                    row["age_s"] = round(now - wall, 3)
            except (OSError, ValueError):
                row["reason"] = "unreadable"
            rows.append(row)
        return {"fleet_dir": self.fleet_dir, "processes": rows}


# MMLSPARK_TPU_FLEET=<dir>: headless fleet export without code changes
# (read once at import; explicit enable()/disable() calls override —
# the same precedence contract as MMLSPARK_TPU_FLIGHT/OBS)
_env_dir = config.get("fleet", None)
if _env_dir:  # pragma: no cover - env-dependent
    enable(str(_env_dir))

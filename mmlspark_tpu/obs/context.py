"""Request-scoped tracing — trace ids across the batcher's thread hops.

The span tracer (``obs/spans.py``) nests per thread, which is exactly
wrong for a served request: its journey is admission on an HTTP handler
thread, packing on the batcher's scheduler thread, dispatch + D2H drain
on a replica lane's worker thread, and resolution back on the caller.
Per-thread timelines shatter that causal chain (the Dapper gap —
Sigelman et al., 2010). This module is the stitch:

* :func:`mint` — a process-unique **trace id**, minted at admission
  (``DynamicBatcher.submit``). One id per request, for its whole life.
* :func:`bind` — a context manager installing a trace id as the
  thread's **active request context**; every span recorded while bound
  carries it in ``SpanRecord.trace``. This is how a span "belongs to" a
  request without threading an argument through every call.
* **links** — fan-in/fan-out edges: a bucket-batch span (pack,
  dispatch, drain) runs on behalf of N coalesced requests at once, so it
  records ``links=(t1, …, tN)`` instead of a single trace
  (``span(..., links=...)``). N request flows converge into the batch
  span on pack and diverge back out at the per-request
  ``serve/complete`` span — rendered as Perfetto flow arrows by
  :func:`mmlspark_tpu.obs.export.chrome_trace`.
* :func:`request_traces` / :func:`check_journey` — the structured read
  side: group captured spans by trace id and validate that one
  request's chain (``REQUEST_JOURNEY``) is intact — what the tier-1
  ``check_obs_request_tracing`` gate asserts for every completed
  request of a dp-fan-out burst.

Everything here is gated the same way as the tracer: ``mint()`` is one
module-flag check returning None when obs is disabled, and a None trace
binds/records as nothing.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable

from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.events import SpanRecord

_tls = threading.local()

# one served request's causal chain, in dispatch order. admit/complete
# are per-request spans carrying the trace id itself; pack/dispatch/
# drain are per-bucket-batch spans carrying it in their links
REQUEST_JOURNEY = ("serve/admit", "serve/pack", "serve/dispatch",
                   "serve/drain", "serve/complete")
# the per-request endpoints of the chain (exactly one of each per trace)
_ENDPOINTS = ("serve/admit", "serve/complete")


def mint() -> int | None:
    """A fresh trace id, or None when the tracer is disabled (one
    module-flag check — the admission hot path's whole disabled cost)."""
    if not _rt._enabled:
        return None
    return _rt.next_trace_id()


def current() -> int | None:
    """The calling thread's active request trace id (None outside any
    bound request)."""
    return getattr(_tls, "trace", None)


class _Bind:
    """Context manager installing (and restoring) the thread's active
    trace id. Re-entrant: the previous binding is saved per instance."""

    __slots__ = ("_trace", "_prev")

    def __init__(self, trace: int | None):
        self._trace = trace

    def __enter__(self) -> int | None:
        self._prev = getattr(_tls, "trace", None)
        _tls.trace = self._trace
        return self._trace

    def __exit__(self, *exc: Any) -> bool:
        _tls.trace = self._prev
        return False


def bind(trace: int | None) -> _Bind:
    """Install ``trace`` as the thread's active request context for the
    ``with`` body; spans recorded inside carry it. ``bind(None)``
    deliberately clears the context (a worker reused across requests
    must not leak the previous request's id into unrelated spans)."""
    return _Bind(trace)


# ---- structured read side ----

def span_trace_ids(record: SpanRecord) -> tuple:
    """Every trace id one span touches: its own trace plus its links."""
    ids = () if record.trace is None else (record.trace,)
    if record.links:
        ids = ids + tuple(record.links)
    return ids


def request_traces(records: Iterable | None = None
                   ) -> dict[int, list[SpanRecord]]:
    """Captured spans grouped by trace id (default: the runtime ring
    buffer), each group sorted by start time — one entry per request
    observed, containing its whole journey including the shared
    bucket-batch spans it was coalesced into.

    Retention is bounded (``obs.enable(max_traces=…)``, default 4096):
    when the ring sees more distinct traces than the bound, the oldest
    are evicted (drop-oldest; counted in ``obs.traces_dropped``) and no
    longer grouped here — a batch span that linked both a live and a
    dropped trace still appears under the live one. An explicit
    ``records`` list bypasses the filter (the caller owns retention)."""
    live: set | None = None
    if records is None:
        records = _rt.spans()
        live = _rt.live_traces()
    out: dict[int, list[SpanRecord]] = {}
    for r in records:
        if not isinstance(r, SpanRecord):
            continue
        for tid in span_trace_ids(r):
            if live is not None and tid not in live:
                continue
            out.setdefault(tid, []).append(r)
    for spans in out.values():
        spans.sort(key=lambda s: (s.start_ns, s.span_id))
    return out


def check_journey(spans: list[SpanRecord],
                  journey: tuple = REQUEST_JOURNEY) -> str | None:
    """None when one request's span chain is intact, else a reason.

    Intact means: exactly one ``serve/admit`` and one ``serve/complete``
    (the per-request endpoints), at least one of every other journey
    span (the batch spans the request was fanned into), and start times
    that respect the causal order admission → pack → dispatch → drain →
    complete. Used for COMPLETED requests — an expired/failed request
    legitimately stops mid-journey."""
    by_name: dict[str, list[SpanRecord]] = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    for name in journey:
        got = by_name.get(name, [])
        if not got:
            return f"missing {name!r} span"
        if name in _ENDPOINTS and len(got) != 1:
            return (f"{len(got)} {name!r} spans for one request "
                    "(want exactly 1)")
    prev_name, prev_start = None, None
    for name in journey:
        start = min(s.start_ns for s in by_name[name])
        if prev_start is not None and start < prev_start:
            return (f"{name!r} starts before {prev_name!r} — the "
                    "causal chain is out of order")
        prev_name, prev_start = name, start
    return None

"""Obs runtime state: the enable flag, the span ring buffer, and the jit
compile-cache hook.

The tracer is OFF by default. Every instrumented seam (plan crossings,
train input, serve dispatch, decode pools) guards itself with one read of
this module's ``_enabled`` flag, so production paths that never enable
observability pay a single attribute load + branch per seam — no
allocation, no lock (the ``< 2%`` disabled-overhead gate in
``tools/perf_smoke.py:check_obs_overhead``).

Enable programmatically (``obs.enable()``), or from the environment with
``MMLSPARK_TPU_OBS=1`` (read once at import through ``core.config``).

The **compile-cache hook** lives here too: reading an XLA program count
off a jitted callable's own compile cache was serve-local in PR 4
(``DynamicBatcher.compiled_programs``); it is the process-wide recompile
observable every layer wants, so :func:`jit_cache_size` /
:func:`compiled_programs` are owned by obs and the serve layer delegates.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Any

from mmlspark_tpu.core import config
from mmlspark_tpu.obs.events import EventRecord, SpanRecord
from mmlspark_tpu.obs.lockwitness import named_lock

DEFAULT_BUFFER = 65536
# distinct request traces retained for grouping (obs/context.py) before
# drop-oldest eviction kicks in — see note_traces below
DEFAULT_MAX_TRACES = 4096

# single module-level flag instrumented seams check; mutate only through
# enable()/disable()
_enabled = False
# when True, spans additionally enter jax.profiler.TraceAnnotation so an
# XProf/Perfetto capture interleaves host spans with the device timeline
_device_annotations = False
# bounded ring buffer of completed SpanRecord/EventRecord (oldest evicted)
_buffer: deque = deque(maxlen=DEFAULT_BUFFER)
_lock = named_lock("obs.runtime._lock")
# total records ever appended — lets the trace evictor compute how many
# records arrived while it filtered outside the lock (len() can't: a
# full ring stays at maxlen while still receiving appends)
_append_seq = 0
# one physical span-eviction at a time; a thread that loses the race
# skips — the live-set filter already bounds what readers group, the
# next eviction round reclaims the spans
_evict_lock = named_lock("obs.runtime._evict_lock")

# ---- trace retention (the request_traces eviction policy) ----
# The span ring is bounded by record COUNT, which bounded nothing per
# TRACE: a sustained request burst filled the ring with thousands of
# completed traces that request_traces() kept grouping (and the export
# kept rendering as flows) until someone called clear(). Retention is
# now explicit: the first `_max_traces` distinct trace ids stay live;
# beyond that the OLDEST traces are dropped in batches — their spans
# evicted from the ring, the drop counted in `obs.traces_dropped` — so
# a server left tracing for days holds a bounded, recent trace set.
_max_traces = DEFAULT_MAX_TRACES
_trace_order: dict[int, None] = {}  # insertion-ordered live trace ids
# recently dropped ids (bounded): a dropped trace whose in-flight spans
# complete later must NOT be resurrected as the "newest" trace — that
# would group a tail-only partial trace and double-count the drop
_dropped_ids: dict[int, None] = {}
_trace_lock = named_lock("obs.runtime._trace_lock")
_traces_dropped = 0


def enable(buffer_size: int = DEFAULT_BUFFER,
           device_annotations: bool = False,
           device: bool | None = None,
           max_traces: int | None = None) -> None:
    """Turn the tracer on. Idempotent; a changed ``buffer_size`` rebuilds
    the ring buffer (keeping the newest records that fit).

    ``device=True`` additionally enables the device-attribution pillar
    (:mod:`mmlspark_tpu.obs.device`: compile-time histograms,
    ``plan.segment.*`` cost/memory gauges, live memory polling) and
    implies ``device_annotations``; ``device=False`` switches it off.
    Omitted kwargs restore their DEFAULTS, not the previous call's
    values — and the default for ``device`` is the environment baseline
    (``MMLSPARK_TPU_OBS_DEVICE``), so a library's plain ``enable()``
    (e.g. ``tools/serve.py --obs``) never silently defeats the
    documented no-code-changes env path. ``max_traces`` re-bounds the
    live request-trace retention (drop-oldest); omitting it restores
    the default bound, same as ``buffer_size`` restores the default
    ring."""
    global _enabled, _device_annotations, _buffer, _max_traces
    dev = (bool(config.get("obs_device", False)) if device is None
           else bool(device))
    with _lock:
        if _buffer.maxlen != buffer_size:
            _buffer = deque(_buffer, maxlen=int(buffer_size))
        _device_annotations = bool(device_annotations) or dev
        _max_traces = (DEFAULT_MAX_TRACES if max_traces is None
                       else max(int(max_traces), 1))
        _enabled = True
    from mmlspark_tpu.obs import device as _device_mod
    if dev:
        _device_mod.enable()
    else:
        _device_mod.disable()


def disable() -> None:
    """Turn the tracer off (records already captured stay readable).
    The device-attribution pillar rides the tracer: it is switched off
    here too (re-enable with ``enable(device=True)``)."""
    global _enabled
    with _lock:
        _enabled = False
    from mmlspark_tpu.obs import device as _device_mod
    _device_mod.disable()


def enabled() -> bool:
    return _enabled


def clear() -> None:
    """Drop captured spans/events, the live-trace retention set, and
    the dropped-trace tally (metrics live in obs.metrics; clear those
    via ``obs.registry().reset()``)."""
    global _traces_dropped
    with _lock:
        _buffer.clear()
    with _trace_lock:
        _trace_order.clear()
        _dropped_ids.clear()
        _traces_dropped = 0


def record(item: SpanRecord | EventRecord) -> None:
    """Append one finished record. The append takes ``_lock`` so it
    serializes against the trace-eviction ring rebuild in
    :func:`note_traces` — a lock-free append could land on the ring
    object being swapped out and silently vanish. (Uncontended acquire
    is ~100 ns on a path that already allocates a record; the disabled
    path never reaches here.) Records carrying request trace ids also
    register in the live-trace set, which enforces the drop-oldest
    retention bound."""
    global _append_seq
    with _lock:
        _buffer.append(item)
        _append_seq += 1
    trace = getattr(item, "trace", None)
    links = getattr(item, "links", None)
    if trace is not None or links:
        note_traces(trace, links)


def note_traces(trace: int | None, links: tuple | None) -> None:
    """Register a record's trace ids as live; evict the oldest traces
    (batched — each eviction rebuilds the ring once) past the bound."""
    global _traces_dropped, _buffer
    with _trace_lock:
        if trace is not None and trace not in _dropped_ids:
            _trace_order.setdefault(trace, None)
        for t in links or ():
            if t not in _dropped_ids:
                _trace_order.setdefault(t, None)
        excess = len(_trace_order) - _max_traces
        if excess <= 0:
            return
        # drop in batches of at least max_traces/8 so the O(ring) span
        # eviction amortizes over many new traces, not one rebuild each
        n_drop = max(excess, _max_traces // 8, 1)
        it = iter(_trace_order)
        dropped = {next(it) for _ in range(min(n_drop,
                                               len(_trace_order)))}
        for t in dropped:
            del _trace_order[t]
            _dropped_ids[t] = None
        _traces_dropped += len(dropped)
        # the resurrection guard is itself bounded: only an id dropped
        # while its request was STILL IN FLIGHT can come back, so
        # remembering the most recent max(max_traces, 1024) drops is
        # plenty (the floor keeps the guard meaningful under a tiny
        # test-sized max_traces; the cost is a few thousand ints)
        cap = max(_max_traces, 1024)
        while len(_dropped_ids) > cap:
            del _dropped_ids[next(iter(_dropped_ids))]
        # filter against the ACCUMULATED dropped memo, not just this
        # round's batch: a round that loses the evict race below skips
        # its rebuild, and only the memo lets a later round reclaim
        # those spans too
        dropped_all = set(_dropped_ids)

    def keep(r) -> bool:
        tr = getattr(r, "trace", None)
        ln = getattr(r, "links", None)
        if tr is None and not ln:
            return True  # non-request records are never trace-evicted
        if tr is not None and tr not in dropped_all:
            return True
        return any(t not in dropped_all for t in ln or ())

    # physically evict the dropped traces' spans — but run the O(ring)
    # Python filter OUTSIDE the record lock: under sustained serve
    # traffic this fires every max_traces/8 new traces, and holding
    # _lock for a 65536-record pass would stall every lane's span
    # completion for milliseconds. The locked sections are two C-level
    # list() copies plus the (small) tail that arrived mid-filter; a
    # concurrent evictor skips — readers already filter by the live
    # set, so deferred spans are invisible until the next round.
    if _evict_lock.acquire(blocking=False):
        try:
            with _lock:
                snapshot = list(_buffer)
                seq0 = _append_seq
            kept = [r for r in snapshot if keep(r)]
            with _lock:
                n_new = min(_append_seq - seq0, len(_buffer))
                tail = list(_buffer)[len(_buffer) - n_new:]
                _buffer = deque(
                    kept + [r for r in tail if keep(r)],
                    maxlen=_buffer.maxlen)
        finally:
            _evict_lock.release()
    from mmlspark_tpu.obs.metrics import registry as _reg
    _reg().counter("obs.traces_dropped").add(len(dropped))


def live_traces() -> set:
    """The trace ids currently retained for grouping (newest
    ``max_traces`` distinct ids seen by the ring)."""
    with _trace_lock:
        return set(_trace_order)


def dropped_trace_count() -> int:
    """Total traces evicted by the retention policy since the last
    :func:`clear`. Mirrors the ``obs.traces_dropped`` registry counter
    when tracer and registry are reset together (``obs.clear()`` +
    ``obs.registry().reset()``, as the test fixtures do); the two
    diverge if only one side is reset."""
    return _traces_dropped


def spans() -> list:
    """Snapshot of captured records, oldest first. (``list(deque)`` is a
    single atomic C call — safe against concurrent ``record()``; a plain
    comprehension over the live deque would raise ``RuntimeError`` when
    another thread appends mid-iteration.)"""
    return list(_buffer)


def captured_count() -> int:
    """O(1) record count (no buffer copy — the /metrics poll path)."""
    return len(_buffer)


def span_records() -> list[SpanRecord]:
    return [r for r in spans() if isinstance(r, SpanRecord)]


# ---- request trace ids (obs/context.py) ----

# process-wide monotonic trace-id source: itertools.count.__next__ is a
# single CPython bytecode step, so ids are unique without a lock even
# when every HTTP handler thread mints at once
_trace_ids = itertools.count(1)


def next_trace_id() -> int:
    """A fresh, process-unique request trace id (never reused; surviving
    ``clear()`` on purpose — a cleared buffer must not let a new request
    collide with ids already serialized into an exported trace)."""
    return next(_trace_ids)


# ---- the jit compile-cache hook (promoted from serve/batcher.py) ----

def jit_cache_size(jitted: Any) -> int | None:
    """XLA executables in one jitted callable's compile cache; ``None``
    when the jit object doesn't expose it (older jax)."""
    size_of = getattr(jitted, "_cache_size", None)
    if size_of is None:
        return None
    return int(size_of())


def compiled_programs(cache_host: Any) -> int | None:
    """Total XLA executables across ``cache_host``'s compiled-segment
    cache (``core.plan._cached_segment``'s store) — the recompile
    observable behind the serve bucket-ladder gate and ``tools/trace.py``.
    ``None`` when any cached jit doesn't expose its cache size; ``0`` for
    a host that never compiled a segment."""
    host_dict = getattr(cache_host, "__dict__", {})
    store = host_dict.get("_plan_cache")
    if not store:
        return 0
    # snapshot under the plan lock: dispatch threads insert/evict entries
    # concurrently, and iterating a mutating dict raises
    lock = host_dict.get("_plan_lock")
    if lock is not None:
        with lock:
            entries = list(store.values())
    else:  # pragma: no cover - cache always created with its lock
        entries = list(store.values())
    total = 0
    for _tokens, compiled, _pinned in entries:
        size = jit_cache_size(compiled[0])
        if size is None:
            return None
        total += size
    return total


# honor MMLSPARK_TPU_OBS=1 (or config.set("obs", True) before first
# import) — the env-var path for tracing a production run without code.
# MMLSPARK_TPU_OBS_DEVICE=1 additionally turns on the device-attribution
# pillar (+ jax.profiler annotations); it implies the tracer. Explicit
# obs.enable(...) kwargs later override both (the env is read ONCE here)
if config.get("obs", False) \
        or config.get("obs_device", False):  # pragma: no cover - env
    enable(device=bool(config.get("obs_device", False)))

"""Obs runtime state: the enable flag, the span ring buffer, and the jit
compile-cache hook.

The tracer is OFF by default. Every instrumented seam (plan crossings,
train input, serve dispatch, decode pools) guards itself with one read of
this module's ``_enabled`` flag, so production paths that never enable
observability pay a single attribute load + branch per seam — no
allocation, no lock (the ``< 2%`` disabled-overhead gate in
``tools/perf_smoke.py:check_obs_overhead``).

Enable programmatically (``obs.enable()``), or from the environment with
``MMLSPARK_TPU_OBS=1`` (read once at import through ``core.config``).

The **compile-cache hook** lives here too: reading an XLA program count
off a jitted callable's own compile cache was serve-local in PR 4
(``DynamicBatcher.compiled_programs``); it is the process-wide recompile
observable every layer wants, so :func:`jit_cache_size` /
:func:`compiled_programs` are owned by obs and the serve layer delegates.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Any

from mmlspark_tpu.core import config
from mmlspark_tpu.obs.events import EventRecord, SpanRecord

DEFAULT_BUFFER = 65536

# single module-level flag instrumented seams check; mutate only through
# enable()/disable()
_enabled = False
# when True, spans additionally enter jax.profiler.TraceAnnotation so an
# XProf/Perfetto capture interleaves host spans with the device timeline
_device_annotations = False
# bounded ring buffer of completed SpanRecord/EventRecord (oldest evicted)
_buffer: deque = deque(maxlen=DEFAULT_BUFFER)
_lock = threading.Lock()


def enable(buffer_size: int = DEFAULT_BUFFER,
           device_annotations: bool = False) -> None:
    """Turn the tracer on. Idempotent; a changed ``buffer_size`` rebuilds
    the ring buffer (keeping the newest records that fit)."""
    global _enabled, _device_annotations, _buffer
    with _lock:
        if _buffer.maxlen != buffer_size:
            _buffer = deque(_buffer, maxlen=int(buffer_size))
        _device_annotations = bool(device_annotations)
        _enabled = True


def disable() -> None:
    """Turn the tracer off (records already captured stay readable)."""
    global _enabled
    with _lock:
        _enabled = False


def enabled() -> bool:
    return _enabled


def clear() -> None:
    """Drop captured spans/events (metrics live in obs.metrics; clear
    those via ``obs.registry().reset()``)."""
    with _lock:
        _buffer.clear()


def record(item: SpanRecord | EventRecord) -> None:
    """Append one finished record (deque.append is atomic under the GIL;
    the ring bound makes the buffer safe to leave enabled forever)."""
    _buffer.append(item)


def spans() -> list:
    """Snapshot of captured records, oldest first. (``list(deque)`` is a
    single atomic C call — safe against concurrent ``record()``; a plain
    comprehension over the live deque would raise ``RuntimeError`` when
    another thread appends mid-iteration.)"""
    return list(_buffer)


def captured_count() -> int:
    """O(1) record count (no buffer copy — the /metrics poll path)."""
    return len(_buffer)


def span_records() -> list[SpanRecord]:
    return [r for r in spans() if isinstance(r, SpanRecord)]


# ---- request trace ids (obs/context.py) ----

# process-wide monotonic trace-id source: itertools.count.__next__ is a
# single CPython bytecode step, so ids are unique without a lock even
# when every HTTP handler thread mints at once
_trace_ids = itertools.count(1)


def next_trace_id() -> int:
    """A fresh, process-unique request trace id (never reused; surviving
    ``clear()`` on purpose — a cleared buffer must not let a new request
    collide with ids already serialized into an exported trace)."""
    return next(_trace_ids)


# ---- the jit compile-cache hook (promoted from serve/batcher.py) ----

def jit_cache_size(jitted: Any) -> int | None:
    """XLA executables in one jitted callable's compile cache; ``None``
    when the jit object doesn't expose it (older jax)."""
    size_of = getattr(jitted, "_cache_size", None)
    if size_of is None:
        return None
    return int(size_of())


def compiled_programs(cache_host: Any) -> int | None:
    """Total XLA executables across ``cache_host``'s compiled-segment
    cache (``core.plan._cached_segment``'s store) — the recompile
    observable behind the serve bucket-ladder gate and ``tools/trace.py``.
    ``None`` when any cached jit doesn't expose its cache size; ``0`` for
    a host that never compiled a segment."""
    host_dict = getattr(cache_host, "__dict__", {})
    store = host_dict.get("_plan_cache")
    if not store:
        return 0
    # snapshot under the plan lock: dispatch threads insert/evict entries
    # concurrently, and iterating a mutating dict raises
    lock = host_dict.get("_plan_lock")
    if lock is not None:
        with lock:
            entries = list(store.values())
    else:  # pragma: no cover - cache always created with its lock
        entries = list(store.values())
    total = 0
    for _tokens, compiled, _pinned in entries:
        size = jit_cache_size(compiled[0])
        if size is None:
            return None
        total += size
    return total


# honor MMLSPARK_TPU_OBS=1 (or config.set("obs", True) before first
# import) — the env-var path for tracing a production run without code
if config.get("obs", False):  # pragma: no cover - env-dependent
    enable()

"""Unified observability — structured tracing, metrics, timeline export.

The reference's observability story is wall-clock logging (the ``Timer``
stage, reference: pipeline-stages/src/main/scala/Timer.scala:54-123). This
repo's hot paths — the fused device plan (``core/plan.py``), the
prefetching train input pipeline (``train/input.py``), and the
dynamic-batching server (``serve/``) — each grew their own accounting;
this package is the ONE telemetry substrate they all record into, in the
spirit of Dapper-style span tracing and the XProf/Perfetto device
timeline:

* :mod:`~mmlspark_tpu.obs.metrics` — a process-wide, thread-safe
  **metrics registry**: counters, gauges, and windowed histograms
  (p50/p95/p99), labeled (model/stage/bucket/loader).
* :mod:`~mmlspark_tpu.obs.spans` — a **structured span/event tracer**:
  nested spans with wall + thread timestamps into a bounded ring buffer.
  Disabled (the default) it is a single module-level flag check returning
  a shared null context — no allocation, no locking.
* :mod:`~mmlspark_tpu.obs.export` — **exporters**: a JSON metrics
  snapshot and Chrome-trace/Perfetto ``trace_event`` JSON; host spans can
  additionally enter ``jax.profiler`` annotations
  (``enable(device_annotations=True)``) so an XProf capture interleaves
  them with the device timeline.
* :mod:`~mmlspark_tpu.obs.runtime` — enable/disable plus the jit
  compile-cache hook (promoted here from the serve layer).
* :mod:`~mmlspark_tpu.obs.context` — **request-scoped tracing**: trace
  ids minted at admission, bound across thread hops, fan-in/fan-out
  span links, and the ``request_traces``/``check_journey`` read side.
* :mod:`~mmlspark_tpu.obs.slo` — the **SLO engine**: declarative
  objectives (``SLOSpec``), windowed error-budget burn rates computed
  from registry reads only (``SLOTracker``), and the train-loop
  slow-step detector.
* :mod:`~mmlspark_tpu.obs.health` — the **ok/degraded/unhealthy state
  machine** (fast/slow burn + reject-ratio classification, hysteretic
  recovery) behind the serving health surfaces.
* :mod:`~mmlspark_tpu.obs.flight` — the **flight recorder**: an
  always-on post-mortem ring + watchdog that dumps recent spans,
  per-thread stacks, and the registry snapshot on crash, signal, or
  hang (``MMLSPARK_TPU_FLIGHT=<dir>``).
* :mod:`~mmlspark_tpu.obs.device` — **device attribution**: per-segment
  compile-time histograms, XLA cost/memory gauges
  (``plan.segment.*``), live device-memory polling, and the
  compute/transfer/idle timeline split.
* :mod:`~mmlspark_tpu.obs.anomaly` — the **train anomaly plane**:
  non-finite loss sentinel (typed :class:`NonFiniteLossError`) and
  multi-host straggler detection (``train.host_skew``).
* :mod:`~mmlspark_tpu.obs.fleet` — the **fleet telemetry plane**:
  per-process atomic snapshot export (``MMLSPARK_TPU_FLEET=<dir>``),
  cross-process registry merge (counters summed bit-exactly, gauges
  per host), and the clock-aligned fleet Perfetto timeline stitched
  at the fenced-collective seams.
* :mod:`~mmlspark_tpu.obs.timeseries` — **metric history**: a periodic
  sampler persisting the SLO/autoscale gauges into a bounded ring +
  append-only JSONL with a small query API (``range``/``rate``/
  ``last``) — the trend signals the adaptive ladder and autoscalers
  need.
* :mod:`~mmlspark_tpu.obs.lockwitness` — the **runtime lock-order
  witness**: ``named_lock``/``named_rlock``/``named_condition``
  factories whose name strings join the static lock-order graph of
  :mod:`mmlspark_tpu.analysis.concurrency`; opt-in edge recording,
  both-order violation detection, and ``crosscheck`` labelling of
  static edges (docs/concurrency.md).

Everything is CPU-safe and jax-free at import time. See
docs/observability.md for the architecture and the instrumented seams.
"""

from mmlspark_tpu.obs.events import EventRecord, SpanRecord  # noqa: F401
from mmlspark_tpu.obs.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, registry,
)
from mmlspark_tpu.obs.runtime import (  # noqa: F401
    clear, compiled_programs, disable, enable, enabled,
)
from mmlspark_tpu.obs.runtime import spans as captured  # noqa: F401
from mmlspark_tpu.obs.spans import event, span  # noqa: F401
from mmlspark_tpu.obs.context import (  # noqa: F401
    REQUEST_JOURNEY, bind, check_journey, mint, request_traces,
)
from mmlspark_tpu.obs.export import (  # noqa: F401
    chrome_trace, metrics_snapshot, prometheus_text, write_chrome_trace,
    write_snapshot,
)
from mmlspark_tpu.obs.slo import (  # noqa: F401
    SLOSpec, SLOTracker, SlowStepDetector,
)
from mmlspark_tpu.obs.health import (  # noqa: F401
    HealthMonitor, HealthPolicy,
)
from mmlspark_tpu.obs import anomaly  # noqa: F401
from mmlspark_tpu.obs import device  # noqa: F401
from mmlspark_tpu.obs import fleet  # noqa: F401
from mmlspark_tpu.obs import flight  # noqa: F401
from mmlspark_tpu.obs import lockwitness  # noqa: F401
from mmlspark_tpu.obs import timeseries  # noqa: F401
from mmlspark_tpu.obs.anomaly import (  # noqa: F401
    NonFiniteLossError, NonFiniteSentinel, StragglerDetector,
)
from mmlspark_tpu.obs.device import (  # noqa: F401
    device_time_split, poll_memory,
)

__all__ = [
    "Counter",
    "EventRecord",
    "Gauge",
    "HealthMonitor",
    "HealthPolicy",
    "Histogram",
    "MetricsRegistry",
    "NonFiniteLossError",
    "NonFiniteSentinel",
    "REQUEST_JOURNEY",
    "SLOSpec",
    "SLOTracker",
    "SlowStepDetector",
    "SpanRecord",
    "StragglerDetector",
    "anomaly",
    "bind",
    "captured",
    "check_journey",
    "chrome_trace",
    "clear",
    "compiled_programs",
    "device",
    "device_time_split",
    "disable",
    "enable",
    "enabled",
    "event",
    "fleet",
    "flight",
    "lockwitness",
    "metrics_snapshot",
    "mint",
    "poll_memory",
    "prometheus_text",
    "registry",
    "request_traces",
    "span",
    "spans",
    "timeseries",
    "write_chrome_trace",
    "write_snapshot",
]

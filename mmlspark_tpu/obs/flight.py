"""Flight recorder — always-on forensics for crashes and hangs.

A hung multi-host step, an OOM'd process, a NaN'd loss, or a wedged
serve lane today leaves nothing behind but a dead process. The flight
recorder is the black box: once enabled it costs one ``is not None``
check per heartbeat seam, and on an **unhandled exception**, a
**SIGTERM/SIGINT**, or a **stalled heartbeat** (a step or dispatch
exceeding its hang threshold) it dumps a self-contained post-mortem
JSON to its configured directory:

* the **recent ring** — the tail of the obs span/event ring buffer
  (enabling the recorder enables the tracer, so the ring is live),
* the **registry snapshot** plus the watchdog's last **metric deltas**
  (what moved — and what stopped moving — in the final poll interval),
* **per-thread stacks** via ``sys._current_frames`` (a hang dump shows
  exactly which frame every worker is stuck in),
* the **heartbeat table** (which lane stalled, for how long),
* a **mesh/config fingerprint** (devices, process index/count, config
  overrides, relevant env) so a dump is interpretable without the box.

Heartbeat seams are wired through ``Trainer.fit_arrays``/``fit_stream``
(one beat per step), ``DeviceLoader``'s producer (one per committed
batch), and every ``DynamicBatcher`` lane (begin/beat/end around
assigned work). A heartbeat only counts as hung while it is *busy* —
an idle serve lane is not a stall.

Enable with ``MMLSPARK_TPU_FLIGHT=<dir>`` (headless runs get forensics
without code changes) or ``obs.flight.enable(dir)``. Render a dump with
``python tools/trace.py postmortem <dump.json>``. Disabled (the
default), every seam is a single module-attribute check — inside the
``check_obs_overhead`` budget, and ``check_flight_recorder`` holds the
dump contract in tier-1.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any

from mmlspark_tpu.core import config
from mmlspark_tpu.obs.lockwitness import named_lock
from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.metrics import registry as _registry

FLIGHT_VERSION = 1
DEFAULT_RING = 2048
DEFAULT_HANG_S = 120.0
DEFAULT_POLL_S = 1.0

THREAD_NAME = "FlightWatchdog"


def _scrub(obj: Any) -> Any:
    """Replace non-finite floats with their string names. Python's
    ``json.dump`` emits bare ``NaN``/``Infinity`` tokens (not valid
    JSON) for them — a dump advertised as self-contained forensics must
    parse in strict off-box consumers (jq, JSON.parse, Go/Rust), and
    registry snapshots DO carry NaN (e.g. a gauge set from a diverged
    loss)."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj in (float("inf"), float("-inf")):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: _scrub(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_scrub(v) for v in obj]
    return obj


class _Heartbeat:
    __slots__ = ("threshold_s", "last_ns", "busy", "beats", "stalled")

    def __init__(self, threshold_s: float):
        self.threshold_s = threshold_s
        self.last_ns = time.perf_counter_ns()
        self.busy = False
        self.beats = 0
        self.stalled = False  # already dumped for the current stall


class FlightRecorder:
    """One process's flight recorder: heartbeats, watchdog, dump."""

    def __init__(self, out_dir: str, ring: int = DEFAULT_RING,
                 hang_threshold_s: float = DEFAULT_HANG_S,
                 poll_s: float = DEFAULT_POLL_S,
                 max_dumps: int = 16):
        self.out_dir = str(out_dir)
        self.ring = int(ring)
        self.hang_threshold_s = float(hang_threshold_s)
        self.poll_s = float(poll_s)
        self.max_dumps = int(max_dumps)
        os.makedirs(self.out_dir, exist_ok=True)
        self._lock = named_lock("obs.flight.FlightRecorder._lock")
        self._beats: dict[str, _Heartbeat] = {}
        self._dumps = 0
        self._seq = 0
        self._stop = threading.Event()
        self._last_counters: dict = {}
        self._last_deltas: dict = {}
        self._prev_hooks: dict = {}
        # the last crash-dumped exception, held STRONGLY: builtin
        # exceptions are not weakref-able, and retaining one exception
        # (+ traceback) until the next crash dump is a bounded price for
        # not double-dumping the on_crash → excepthook path
        self._last_exc: BaseException | None = None
        self._thread = threading.Thread(target=self._watch,
                                        name=THREAD_NAME, daemon=True)
        self._thread.start()

    # ---- heartbeats (the hot-path surface: dict writes, no lock on
    #      beat — a torn read in the watchdog only delays detection by
    #      one poll) ----

    def arm(self, name: str, threshold_s: float | None = None) -> None:
        """Register (or re-arm) a heartbeat and mark it busy."""
        hb = self._beats.get(name)
        if hb is None:
            with self._lock:
                hb = self._beats.get(name)
                if hb is None:
                    hb = self._beats[name] = _Heartbeat(
                        threshold_s if threshold_s is not None
                        else self.hang_threshold_s)
        if threshold_s is not None:
            hb.threshold_s = float(threshold_s)
        hb.last_ns = time.perf_counter_ns()
        hb.busy = True
        hb.stalled = False

    def beat(self, name: str) -> None:
        """One unit of progress; marks the heartbeat busy (creates it
        armed if the seam beat before arming), so beat-on-work /
        disarm-on-idle seams re-arm themselves when work resumes."""
        hb = self._beats.get(name)
        if hb is None:
            self.arm(name)
            hb = self._beats[name]
        hb.last_ns = time.perf_counter_ns()
        hb.beats += 1
        hb.busy = True
        hb.stalled = False

    def disarm(self, name: str) -> None:
        """Mark a heartbeat idle — idle seams are never hangs."""
        hb = self._beats.get(name)
        if hb is not None:
            hb.busy = False
            hb.stalled = False

    def forget(self, name: str) -> None:
        """Remove a heartbeat whose seam is gone for good (a closed
        serve batcher's scheduler/lanes): long-lived processes with
        model churn must not accumulate dead idle entries that bloat
        every dump's heartbeat table."""
        with self._lock:
            self._beats.pop(name, None)

    def heartbeats(self) -> dict[str, dict]:
        now = time.perf_counter_ns()
        with self._lock:
            items = list(self._beats.items())
        return {name: {"busy": hb.busy, "beats": hb.beats,
                       "age_s": round((now - hb.last_ns) / 1e9, 3),
                       "threshold_s": hb.threshold_s}
                for name, hb in items}

    # ---- watchdog ----

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._poll_metrics()
                now = time.perf_counter_ns()
                with self._lock:
                    items = list(self._beats.items())
                for name, hb in items:
                    if not hb.busy or hb.stalled:
                        continue
                    age_s = (now - hb.last_ns) / 1e9
                    if age_s > hb.threshold_s:
                        hb.stalled = True  # one dump per stall
                        self.dump("hang", extra={
                            "heartbeat": name,
                            "stalled_for_s": round(age_s, 3),
                            "threshold_s": hb.threshold_s,
                        })
            except Exception:  # pragma: no cover - watchdog never dies
                pass

    def _poll_metrics(self) -> None:
        """Track counter movement between polls — a dump's 'what moved
        (and what stopped moving) right before the end'."""
        try:
            counters = _registry().snapshot()["counters"]
        except Exception:  # pragma: no cover - defensive
            return
        prev = self._last_counters
        self._last_deltas = {
            k: v - prev.get(k, 0) for k, v in counters.items()
            if v != prev.get(k, 0)
        }
        self._last_counters = counters
        # live device memory rides the same poll when the device pillar
        # is on (dryrun-safe: a backend without memory_stats is a no-op)
        from mmlspark_tpu.obs import device as _device
        if _device._enabled:
            _device.poll_memory()

    # ---- the dump ----

    def _thread_stacks(self) -> dict[str, dict]:
        names = {t.ident: t.name for t in threading.enumerate()}
        out: dict[str, dict] = {}
        for tid, frame in sys._current_frames().items():
            stack = traceback.format_stack(frame)
            out[str(tid)] = {
                "name": names.get(tid, f"thread-{tid}"),
                "stack": [line.rstrip("\n") for line in stack],
            }
        return out

    def _fingerprint(self) -> dict:
        fp: dict[str, Any] = {
            "python": sys.version.split()[0],
            "argv": list(sys.argv),
            "env": {k: v for k, v in os.environ.items()
                    if k.startswith(("MMLSPARK_TPU_", "JAX_", "XLA_"))},
            "config_overrides": dict(config._overrides),
        }
        # never initialize a backend from the dump path: a crash dump in
        # a process that never touched jax must stay jax-free
        if "jax" in sys.modules:
            try:
                import jax
                fp["mesh"] = {
                    "process_index": int(jax.process_index()),
                    "process_count": int(jax.process_count()),
                    "local_devices": [str(d) for d in jax.local_devices()],
                    "device_count": int(jax.device_count()),
                }
            except Exception:
                fp["mesh"] = "unavailable (backend not initialized)"
        return fp

    def dump(self, reason: str, exc: BaseException | None = None,
             extra: dict | None = None) -> str | None:
        """Write one post-mortem JSON; returns its path (None once the
        dump budget is exhausted — a crash loop must not fill the disk).
        Safe to call from any thread, including signal handlers and the
        watchdog; the write is atomic (temp file + rename). One dump per
        exception OBJECT: the train fit loops dump at the failure point
        via ``on_crash`` before re-raising, and the same exception then
        reaches the chained ``sys.excepthook`` — without dedup every
        crash would burn two dump-budget slots and leave duplicate
        forensics."""
        with self._lock:
            if exc is not None:
                if self._last_exc is exc:
                    return None  # already dumped (on_crash → excepthook)
                self._last_exc = exc
            if self._dumps >= self.max_dumps:
                return None
            self._dumps += 1
            self._seq += 1
            seq = self._seq
        payload: dict[str, Any] = {
            "flight": FLIGHT_VERSION,
            "reason": reason,
            "time_unix": time.time(),
            "pid": os.getpid(),
            "ring": [r.to_dict() for r in _rt.spans()[-self.ring:]],
            "registry": _registry().snapshot(),
            "metric_deltas": dict(self._last_deltas),
            "threads": self._thread_stacks(),
            "heartbeats": self.heartbeats(),
            "fingerprint": self._fingerprint(),
        }
        if exc is not None:
            payload["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
            }
        if extra:
            payload["extra"] = extra
        path = os.path.join(
            self.out_dir, f"flight_{reason}_{os.getpid()}_{seq}.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(_scrub(payload), fh, default=str)
            os.replace(tmp, path)
        except OSError:  # pragma: no cover - dump dir vanished
            return None
        if _rt._enabled:
            from mmlspark_tpu.obs.spans import event as _event
            _event("flight/dump", "flight", {"reason": reason,
                                             "path": path})
        _registry().counter("flight.dumps", reason=reason).add()
        # fleet-plane hook, order PINNED dump-then-snapshot: the local
        # post-mortem is on disk first, then the fleet exporter (one
        # attribute check when off) flushes a final snapshot naming it —
        # so the fleet directory's last word about this process is
        # current at the failure point, not a full watchdog interval
        # stale, and points collectors at the richer local dump
        from mmlspark_tpu.obs import fleet as _fleet
        if _fleet._exp is not None:
            _fleet.on_flight_dump(reason, path)
        return path

    # ---- crash/signal hooks ----

    def install(self) -> None:
        """Chain into sys.excepthook, threading.excepthook, and (main
        thread only) the SIGTERM/SIGINT handlers: dump, then defer to
        whatever was installed before."""
        prev_except = sys.excepthook

        def _excepthook(tp, val, tb):
            try:
                self.dump("crash", exc=val)
            except Exception:
                pass
            prev_except(tp, val, tb)

        sys.excepthook = _excepthook
        self._prev_hooks["excepthook"] = prev_except

        prev_thread = threading.excepthook

        def _thread_hook(args):
            try:
                self.dump("crash", exc=args.exc_value, extra={
                    "thread": getattr(args.thread, "name", None)})
            except Exception:
                pass
            prev_thread(args)

        threading.excepthook = _thread_hook
        self._prev_hooks["thread_excepthook"] = prev_thread

        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                prev = signal.getsignal(signum)

                def _handler(num, frame, _prev=prev):
                    # dump on a helper thread, join bounded: a signal
                    # handler runs between bytecodes of the MAIN thread,
                    # which may itself hold a (non-reentrant) registry /
                    # ring lock the dump needs — dumping inline would
                    # deadlock the handler and leave the process
                    # ignoring SIGTERM forever. If the main thread does
                    # hold such a lock, the join times out and we
                    # terminate without a dump rather than hang.
                    try:
                        t = threading.Thread(
                            target=self.dump, args=("signal",),
                            kwargs={"extra": {
                                "signal": signal.Signals(num).name}},
                            name="FlightSignalDump", daemon=True)
                        t.start()
                        t.join(timeout=10.0)
                    except Exception:
                        pass
                    if callable(_prev):
                        _prev(num, frame)
                    elif _prev is signal.SIG_DFL:
                        signal.signal(num, signal.SIG_DFL)
                        signal.raise_signal(num)

                signal.signal(signum, _handler)
                self._prev_hooks[signum] = prev
            except (ValueError, OSError):  # pragma: no cover - not main
                pass  # thread — signal hooks are main-thread-only

    def uninstall(self) -> None:
        hook = self._prev_hooks.pop("excepthook", None)
        if hook is not None:
            sys.excepthook = hook
        hook = self._prev_hooks.pop("thread_excepthook", None)
        if hook is not None:
            threading.excepthook = hook
        for signum in (signal.SIGTERM, signal.SIGINT):
            prev = self._prev_hooks.pop(signum, None)
            if prev is not None:
                try:
                    signal.signal(signum, prev)
                except (ValueError, OSError):  # pragma: no cover
                    pass

    def close(self) -> None:
        self.uninstall()
        self._stop.set()
        self._thread.join(timeout=5.0)


# ---- module surface (the seams check ONE attribute: `_rec`) ----

_rec: FlightRecorder | None = None


def enable(out_dir: str | None = None, **kwargs: Any) -> FlightRecorder:
    """Start the flight recorder. Idempotent for the same directory
    with no kwargs OR the same kwargs the live recorder was built with
    — an "ensure forensics on" call at the top of every work cycle must
    NOT tear down and rebuild the recorder (that would reset the
    ``max_dumps`` disk-fill budget mid-crash-loop, wipe armed
    heartbeats and the crash-dedup state, and unhook/re-hook the crash
    handlers through an uncovered window). Also enables the obs tracer
    — the ring it dumps is the span buffer. ``kwargs`` forward to
    :class:`FlightRecorder` (``ring``, ``hang_threshold_s``,
    ``poll_s``, ``max_dumps``)."""
    global _rec
    out_dir = out_dir or config.get("flight") or "./flight"
    if _rec is not None:
        if _rec.out_dir == str(out_dir) and (
                not kwargs or kwargs == _rec._init_kwargs):
            return _rec
        _rec.close()
        _rec = None
    if not _rt._enabled:  # an already-enabled tracer keeps its ring
        _rt.enable()      # size — never stomp a custom buffer_size
    rec = FlightRecorder(out_dir, **kwargs)
    rec._init_kwargs = dict(kwargs)
    rec.install()
    _rec = rec
    return rec


def disable() -> None:
    """Stop the watchdog and restore the crash/signal hooks (captured
    dumps stay on disk). Does NOT disable the obs tracer."""
    global _rec
    if _rec is not None:
        _rec.close()
        _rec = None


def enabled() -> bool:
    return _rec is not None


def recorder() -> FlightRecorder | None:
    return _rec


def on_crash(exc: BaseException, context: str) -> str | None:
    """Explicit crash hook for loops that may be caught upstream (the
    train fit loops call this before re-raising): the dump happens at
    the failure point even if a caller later swallows the exception."""
    if _rec is None:
        return None
    return _rec.dump("crash", exc=exc, extra={"context": context})


# MMLSPARK_TPU_FLIGHT=<dir>: headless forensics without code changes.
# Explicit enable()/disable() calls override the env (read once here)
_env_dir = config.get("flight", None)
if _env_dir:  # pragma: no cover - env-dependent
    enable(str(_env_dir))

"""Train-path anomaly plane — non-finite sentinel and straggler detection.

The serving path got its anomaly machinery in the SLO engine
(``obs/slo.py``: burn rates, the :class:`SlowStepDetector`); this module
is the training-side counterpart, built on the same registry/event
substrate:

* :class:`NonFiniteSentinel` — a NaN/Inf loss today surfaces (if ever)
  as garbage history values many steps later. The sentinel rides the
  **already-lagged** loss fetches of ``Trainer.fit_arrays``/
  ``fit_stream`` (no new host sync — the fetch exists for the loss
  history), fires **exactly once per offending step**, records a
  ``train.nonfinite_losses{loop=…}`` counter plus a ``train/nonfinite``
  event, and — in the default ``"raise"`` mode — raises the typed
  :class:`NonFiniteLossError` so the run dies AT the divergence with a
  flight-recorder dump, not hours later. ``TrainConfig.nonfinite_loss``
  selects ``"raise"`` / ``"event"`` (record but continue) / ``"off"``.
* :class:`StragglerDetector` — multi-host training is as fast as its
  slowest host, and a straggler is invisible from any single process.
  The consumer loop feeds per-step dispatch times in; the producer
  exchanges each host's recent mean **through the existing
  drain-barrier-fenced liveness allgather** of ``fit_stream`` (the
  step-time pair rides the same collective as the batch counts — no new
  exchange site, so the SPMD203 fence discipline holds by construction),
  and every host publishes ``train.host_skew{loop=…}`` and flags the
  slow host with a ``train/straggler`` event naming its process index.

:class:`~mmlspark_tpu.obs.slo.SlowStepDetector` (the single-host
step-outlier detector this generalizes) is re-exported here so the
anomaly plane is one import surface.
"""

from __future__ import annotations

import math
import threading

import numpy as np

from mmlspark_tpu.obs import runtime as _rt
from mmlspark_tpu.obs.metrics import registry as _registry
from mmlspark_tpu.obs.slo import SlowStepDetector  # noqa: F401 — re-export
from mmlspark_tpu.obs.spans import event as _event

NONFINITE_MODES = ("raise", "event", "off")


class NonFiniteLossError(RuntimeError):
    """The training loss went NaN/Inf. Carries the offending step and
    value so the failure is actionable without re-running."""

    def __init__(self, loop: str, step: int, value: float):
        self.loop = loop
        self.step = step
        self.value = value
        super().__init__(
            f"{loop}: loss became non-finite ({value}) at global step "
            f"{step} — the run has diverged (bad learning rate, bad "
            "batch, or numerical overflow). Set TrainConfig."
            "nonfinite_loss='event' to record-and-continue instead")


class NonFiniteSentinel:
    """Check each (lagged) fetched loss value; fire once per bad step.

    The check itself is a ``math.isfinite`` on a float the loop already
    fetched — zero additional device syncs. Counters/events record only
    when the tracer is enabled; the typed raise works regardless (a
    correctness guard must not depend on telemetry being on)."""

    __slots__ = ("loop", "mode", "_last_step")

    def __init__(self, loop: str, mode: str = "raise"):
        if mode not in NONFINITE_MODES:
            raise ValueError(
                f"nonfinite_loss must be one of {NONFINITE_MODES}: "
                f"{mode!r}")
        self.loop = loop
        self.mode = mode
        self._last_step: int | None = None

    def check(self, step: int, value: float) -> float:
        """Validate one fetched loss; returns it as a float. Exactly one
        counter/event/raise per offending step even if the same step's
        value is consulted twice."""
        value = float(value)
        if self.mode == "off" or math.isfinite(value):
            return value
        if step == self._last_step:
            return value  # this step already fired
        self._last_step = step
        if _rt._enabled:
            _registry().counter("train.nonfinite_losses",
                                loop=self.loop).add()
            _event("train/nonfinite", "train",
                   {"loop": self.loop, "step": int(step),
                    "value": str(value)})
        if self.mode == "raise":
            raise NonFiniteLossError(self.loop, int(step), value)
        return value


class StragglerDetector:
    """Per-host step-time skew over the multi-host liveness exchange.

    ``observe(dur_ms)`` accumulates step dispatch times on the consumer
    thread; ``local_mean_ms()`` drains the accumulator on the producer
    thread (the value that rides the fenced allgather); ``ingest``
    takes the gathered ``[nproc]`` vector of per-host means, publishes
    the ``train.host_skew`` gauge ((max − min) / max ∈ [0, 1]) and
    per-host ``train.host_step_ms`` gauges, and flags the slowest host
    with a ``train/straggler`` event + ``train.stragglers`` counter when
    its mean exceeds ``factor ×`` the median of the *other* active
    hosts (leave-one-out — a self-inclusive median can never flag the
    slow half of a 2-host mesh). Hosts that
    contributed no steps in the window (mean 0 — filler-only blocks)
    are excluded from the baseline but can still be named slow by their
    peers' exchange."""

    __slots__ = ("loop", "factor", "_lock", "_sum_ms", "_count", "last")

    def __init__(self, loop: str, factor: float = 2.0):
        self.loop = loop
        self.factor = float(factor)
        self._lock = threading.Lock()
        self._sum_ms = 0.0
        self._count = 0
        self.last: dict | None = None  # most recent ingest verdict

    # -- consumer side --

    def observe(self, dur_ms: float) -> None:
        with self._lock:
            self._sum_ms += float(dur_ms)
            self._count += 1

    # -- producer side (at the fenced exchange) --

    def local_mean_ms(self) -> float:
        """Mean step time since the last exchange; 0.0 with no steps
        (the no-data marker peers exclude from the baseline)."""
        with self._lock:
            mean = self._sum_ms / self._count if self._count else 0.0
            self._sum_ms = 0.0
            self._count = 0
        return mean

    def ingest(self, host_means_ms: np.ndarray,
               process_index: int = 0) -> dict | None:
        """Evaluate one gathered ``[nproc]`` step-time vector; publishes
        gauges/events and returns the verdict dict (None when no host
        reported any steps this window)."""
        means = np.asarray(host_means_ms, np.float64).reshape(-1)
        active = means[means > 0.0]
        if active.size == 0:
            return None
        hi = float(means.max())
        lo = float(active.min())
        skew = 0.0 if hi <= 0 else (hi - lo) / hi
        slow_host = int(np.argmax(means))
        # baseline = the OTHER active hosts: including the candidate in
        # its own median makes a 2-host straggler unflaggable (hi >
        # factor*(hi+lo)/2 has no solution for factor >= 2), and the
        # 2-process mesh is the common multi-host config
        idx_active = np.flatnonzero(means > 0.0)
        baseline = means[idx_active[idx_active != slow_host]]
        median = float(np.median(baseline)) if baseline.size else 0.0
        is_straggler = (baseline.size > 0 and median > 0.0
                        and hi > self.factor * median)
        verdict = {
            "loop": self.loop,
            "host_means_ms": [round(float(m), 3) for m in means],
            "skew": round(skew, 4),
            "slow_host": slow_host,
            "median_ms": round(median, 3),
            "straggler": is_straggler,
        }
        self.last = verdict
        if _rt._enabled:
            reg = _registry()
            reg.gauge("train.host_skew", loop=self.loop).set(skew)
            for host, mean in enumerate(means):
                reg.gauge("train.host_step_ms", loop=self.loop,
                          host=host).set(round(float(mean), 3))
            if is_straggler:
                reg.counter("train.stragglers", loop=self.loop).add()
                _event("train/straggler", "train",
                       {"loop": self.loop, "host": slow_host,
                        "step_ms": round(hi, 3),
                        "median_ms": round(median, 3),
                        "observed_from": int(process_index)})
        return verdict

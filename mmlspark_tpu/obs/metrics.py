"""Process-wide, thread-safe metrics registry.

Three primitive kinds, all labelable (``model=...``, ``stage=...``,
``bucket=...``):

* :class:`Counter` — monotonically increasing totals (crossings, bytes,
  compiles, requests). Accepts float increments so accumulated seconds
  fit the same primitive.
* :class:`Gauge` — last-written value (queue depth, input-bound
  fraction).
* :class:`Histogram` — bounded-window observation reservoir with
  p50/p95/p99 plus lifetime count/sum (latencies, occupancy). The window
  bounds memory on long-lived processes; ``count``/``sum`` stay exact.

A :class:`MetricsRegistry` interns metrics by ``(name, labels)`` so every
call site asking for the same series gets the SAME object — recording is
then lock-per-metric, never a registry-wide lock. The module-level
:func:`registry` is the process-wide default every instrumented layer
records into; subsystems that need instance-local lifetimes (e.g. one
:class:`~mmlspark_tpu.serve.stats.ServerStats` per loaded model) build
their own private ``MetricsRegistry`` from the same primitives.

Recording is always allowed whether or not tracing is enabled — the
*instrumented call sites* gate themselves on ``obs.enabled()`` so the
disabled path stays a flag check (see docs/observability.md).
"""

from __future__ import annotations

import threading

from mmlspark_tpu.obs.lockwitness import named_lock
from collections import deque
from typing import Any, Iterator

import numpy as np


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def format_series(name: str, labels: tuple) -> str:
    """``name{k=v,...}`` — the snapshot key (Prometheus-style)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic total. ``add`` is thread-safe; negative deltas raise."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: tuple = ()):
        self.name = name
        self.labels = labels
        self._lock = named_lock("obs.metrics.Counter._lock")
        self._value = 0.0

    def add(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r}: negative add {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-written value (thread-safe set/add)."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: tuple = ()):
        self.name = name
        self.labels = labels
        self._lock = named_lock("obs.metrics.Gauge._lock")
        self._value: float | None = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self._value = (self._value or 0.0) + n

    @property
    def value(self) -> float | None:
        with self._lock:
            return self._value


class Histogram:
    """Windowed observation reservoir with exact lifetime count/sum.

    ``percentiles()`` interpolates p50/p95/p99 over the latest ``window``
    observations exactly the way the pre-obs serve stats did
    (``np.percentile`` linear interpolation), so re-backed snapshots are
    value-identical.
    """

    __slots__ = ("name", "labels", "window", "_lock", "_values", "_count",
                 "_sum")

    def __init__(self, name: str, labels: tuple = (), window: int = 4096):
        self.name = name
        self.labels = labels
        self.window = int(window)
        self._lock = named_lock("obs.metrics.Histogram._lock")
        self._values: deque = deque(maxlen=self.window)
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._values.append(v)
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def values(self) -> list[float]:
        """The current window (oldest first)."""
        with self._lock:
            return list(self._values)

    def mean(self, ndigits: int | None = 3) -> float | None:
        """Mean over the WINDOW; None before any observation (the
        pre-traffic-snapshot safety contract)."""
        with self._lock:
            if not self._values:
                return None
            m = float(np.mean(self._values))
        return round(m, ndigits) if ndigits is not None else m

    def percentiles(self, ndigits: int | None = 3) -> dict | None:
        """``{"p50":, "p95":, "p99":, "n":}`` over the window; None when
        empty — callers never divide by zero or percentile an empty
        array."""
        with self._lock:
            if not self._values:
                return None
            arr = np.asarray(self._values, dtype=np.float64)
        p50, p95, p99 = np.percentile(arr, [50, 95, 99])
        if ndigits is not None:
            p50, p95, p99 = (round(float(p), ndigits)
                             for p in (p50, p95, p99))
        return {"p50": float(p50), "p95": float(p95), "p99": float(p99),
                "n": int(arr.size)}


class MetricsRegistry:
    """Interning factory + snapshot surface for one metrics namespace."""

    def __init__(self) -> None:
        self._lock = named_lock("obs.metrics.MetricsRegistry._lock")
        self._metrics: dict[tuple, Any] = {}

    def _get(self, kind: type, name: str, labels: dict,
             **kwargs: Any) -> Any:
        key = (kind.__name__, name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = kind(name, _label_key(labels), **kwargs)
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, window: int = 4096,
                  **labels: Any) -> Histogram:
        return self._get(Histogram, name, labels, window=window)

    def iter_metrics(self) -> Iterator[Any]:
        with self._lock:
            items = list(self._metrics.values())
        yield from items

    def series(self, name: str) -> list[Any]:
        """Every metric registered under ``name`` (one per label set)."""
        return [m for m in self.iter_metrics() if m.name == name]

    def value(self, name: str, **labels: Any) -> float | None:
        """Read a counter/gauge value without creating the series."""
        key_c = ("Counter", name, _label_key(labels))
        key_g = ("Gauge", name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key_c) or self._metrics.get(key_g)
        return None if m is None else m.value

    def snapshot(self) -> dict:
        """One JSON-safe dict: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}`` keyed ``name{label=value,...}``."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.iter_metrics():
            key = format_series(m.name, m.labels)
            if isinstance(m, Counter):
                v = m.value
                out["counters"][key] = int(v) if v == int(v) else v
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][key] = {
                    "count": m.count,
                    "sum": round(m.sum, 6),
                    "mean_window": m.mean(),
                    "percentiles": m.percentiles(),
                }
        return out

    def reset(self) -> None:
        """Drop every registered series (test isolation)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry every instrumented seam uses."""
    return _REGISTRY

"""Benchmark: CIFAR-10 ConvNet train throughput on the local accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The reference publishes no performance numbers (BASELINE.md), so
``vs_baseline`` is reported against the driver-defined north star:
achieved MFU / 0.60 target MFU on the CIFAR-10 CNN featurize+train path.
"""

from __future__ import annotations

import json
import time

import numpy as np


def conv_flops_per_example(module, input_spec) -> float:
    """Analytic forward FLOPs for the ConvNet (2*MACs); backward ≈ 2x fwd."""
    h, w, cin = input_spec
    flops = 0.0
    for width in module.widths:
        for _ in range(2):  # two convs per block
            flops += 2 * h * w * 3 * 3 * cin * width
            cin = width
        h, w = h // 2, w // 2
    flat = h * w * cin
    flops += 2 * flat * module.dense_width
    flops += 2 * module.dense_width * module.num_classes
    return flops


def peak_flops_per_chip() -> float | None:
    """bf16 peak for the local accelerator; None if the device is unknown
    (CPU/GPU dev boxes), in which case MFU is not reported."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12, "v4": 275e12,
        "v5p": 459e12, "v6": 918e12, "v6e": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return None


def main() -> None:
    import jax

    from mmlspark_tpu.models.zoo import ConvNetCifar
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    batch = 512
    module = ConvNetCifar()
    cfg = TrainConfig(batch_size=batch, epochs=1, optimizer="momentum",
                      learning_rate=0.01, log_every=10**9)
    trainer = Trainer(module, cfg)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=batch)

    trainer.state = trainer.init_state(x.shape[1:])
    # batches must be committed to the dp sharding: the jit infers shardings
    # from its args, so an uncommitted numpy batch would replicate (each chip
    # redundantly computing the full batch) and skew per-chip throughput
    from mmlspark_tpu.parallel.mesh import batch_sharding
    data = batch_sharding(trainer.mesh)
    x = jax.device_put(x, data)
    y = jax.device_put(y, data)
    # warmup/compile
    state, _ = trainer.step(trainer.state, x, y)
    jax.block_until_ready(state["params"])

    steps = 30
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.step(state, x, y)
    jax.block_until_ready(state["params"])
    dt = time.perf_counter() - t0

    n_dev = jax.device_count()
    images_per_s_per_chip = steps * batch / dt / n_dev
    # fwd + bwd ≈ 3x forward FLOPs
    step_flops = 3 * conv_flops_per_example(module, (32, 32, 3)) * batch
    peak = peak_flops_per_chip()
    device = jax.devices()[0].device_kind
    if peak is None:
        vs_baseline = None  # unknown hardware: MFU ratio would be garbage
    else:
        mfu = steps * step_flops / dt / (peak * n_dev)
        vs_baseline = round(mfu / 0.60, 4)

    print(json.dumps({
        "metric": "images/sec/chip (CIFAR-10 CNN train)",
        "value": round(images_per_s_per_chip, 1),
        "unit": "images/s/chip",
        "vs_baseline": vs_baseline,
        "device": device,
    }))


if __name__ == "__main__":
    main()

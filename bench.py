"""Benchmark: CIFAR-10 ConvNet train throughput on the local accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The reference publishes no performance numbers (BASELINE.md), so
``vs_baseline`` is reported against the driver-defined north star:
achieved MFU / 0.60 target MFU on the CIFAR-10 CNN featurize+train path.

``python bench.py --check`` additionally runs the perf-regression
sentinel (tools/bench_check.py) over this line vs the archived
``BENCH_r*.json`` trajectory after the obs archiving step: the verdict
lands in the JSON line (``bench_check_verdict``) and a regression exits
2 with the named report on stderr.
"""

from __future__ import annotations

import json
import time

import numpy as np

# the driver-facing series identity — shared by the success and error
# records so a failed round can never mislabel its metric
METRIC_NAME = "images/sec/chip (CIFAR-10 CNN train)"
METRIC_UNIT = "images/s/chip"


def conv_flops_per_example(module, input_spec) -> float:
    """Analytic forward FLOPs for the ConvNet (2*MACs); backward ≈ 2x fwd."""
    h, w, cin = input_spec
    flops = 0.0
    for width in module.widths:
        for _ in range(2):  # two convs per block
            flops += 2 * h * w * 3 * 3 * cin * width
            cin = width
        h, w = h // 2, w // 2
    flat = h * w * cin
    flops += 2 * flat * module.dense_width
    flops += 2 * module.dense_width * module.num_classes
    return flops


def peak_flops_per_chip() -> float | None:
    """bf16 peak for the local accelerator; None if the device is unknown
    (CPU/GPU dev boxes), in which case MFU is not reported."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12, "v4": 275e12,
        "v5p": 459e12, "v6": 918e12, "v6e": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return None


def compiled_flops(jitted_fn, *args) -> float | None:
    """Per-call FLOPs from XLA's own cost model (honest analytic MFU).

    Pass the ALREADY-jitted callable used for timing so the lowering hits
    the jit cache instead of recompiling the model a second time."""
    try:
        cost = jitted_fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost["flops"])
    except Exception:
        return None


def _bench_loop(run_once, passes: int = 5, steps: int = 15) -> float:
    """RTT-cancelling paired timed windows; returns seconds per call.

    Each window ends on a host fetch of a value data-dependent on the LAST
    call — block_until_ready is not a reliable barrier through
    remote-device tunnels, so async dispatch could otherwise end the clock
    before the compute finishes. The fetch itself costs one tunnel
    round-trip *regardless of size*, and the RTT regime drifts between
    rounds (~50 ms r2 → ~85-110 ms r5; PERF_NOTES), so a single window of
    n steps reads as ``t + RTT/n``. Differencing two window lengths
    cancels the additive RTT exactly: ``dt = (T(7n) − T(n)) / 6n``.

    Error budget: the difference carries *signed* noise ±ΔRTT/6n (an RTT
    swing between the paired windows), so (a) the span is wide (7n — a
    ±30 ms swing at n=15 is ±0.33 ms, vs ±1 ms with the earlier 3n span,
    which once read an 8k³ matmul at an impossible 321 TF/s), (b) the
    pass aggregate is the MEDIAN of 5, never the min (min selects
    underestimates), and (c) each pass is clamped to its long-window
    quotient (an RTT-inflated upper bound on optimism)."""
    import jax
    import jax.numpy as jnp
    fetch = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32)))
    # warm the fetch OUTSIDE the timed windows: it is a fresh jit per
    # _bench_loop call, and its first execution (trace+compile+round-trip)
    # inside pass 1's short window would bias that pass's difference
    float(fetch(run_once()))

    def window(n: int) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            out = run_once()
        float(fetch(out))
        return time.perf_counter() - t0

    dts = []
    for _ in range(passes):
        t_short, t_long = window(steps), window(7 * steps)
        dt = (t_long - t_short) / (6 * steps)
        quotient = t_long / (7 * steps)  # RTT-inflated upper bound
        if dt <= 0:  # pathological tunnel noise: fall back to the quotient
            dt = quotient
        dts.append(min(dt, quotient))
    dts.sort()
    return dts[len(dts) // 2]


def bench_flagship_models(rng, n_dev: int, peak: float | None) -> dict:
    """BASELINE configs 3-5: ResNet-50 featurize, BiLSTM-613 tagging,
    ViT-B/16 fine-tune step (single-chip; DP scales via the mesh)."""
    import jax
    import jax.numpy as jnp

    out: dict = {}

    # --- config 3: ResNet-50 image featurization (img/s + MFU) ---
    # The featurize task is frozen-backbone inference, so the benchmarked
    # model is the zoo's *inference variant*: frozen BatchNorm folded into
    # the conv weights (the reference's zoo ResNet-50 is a BN network whose
    # inference-time norm cost folds away — Schema.scala:54-74), bf16
    # params, space-to-depth stem. Same math as the unfolded net
    # (numerics-parity-tested, tests/test_models.py); measured r5: GN
    # train variant 0.39 MFU → folded 0.64 MFU.
    try:
        from mmlspark_tpu.models.zoo import get_model
        bundle = get_model("ResNet50_Infer", num_classes=10, input_size=224)
        params = jax.device_put(bundle.params, jax.devices()[0])
        batch = 256
        x = jnp.asarray(rng.integers(0, 255, (batch, 224, 224, 3)
                                     ).astype(np.float32))

        def fwd(p, xb):
            return bundle.module.apply({"params": p}, xb, output="features")

        fn = jax.jit(fwd)
        fn(params, x).block_until_ready()  # compile
        dt = _bench_loop(lambda: fn(params, x))
        out["resnet50_featurize_images_per_s_per_chip"] = round(
            batch / dt, 1)
        out["resnet50_featurize_variant"] = "folded-frozen-bn+s2d+bf16"
        flops = compiled_flops(fn, params, x)
        if flops and peak:
            out["resnet50_featurize_mfu"] = round(flops / dt / peak, 4)
    except Exception as e:
        out["resnet50_featurize_images_per_s_per_chip"] = f"error: {e}"

    # --- config 4: BiLSTM tagger at the reference's 613-token pad ---
    try:
        from mmlspark_tpu.models.zoo import get_model
        bundle = get_model("BiLSTM_MedTag", vocab_size=8192, num_tags=16,
                           max_len=613)
        params = jax.device_put(bundle.params, jax.devices()[0])
        batch = 64
        toks = jnp.asarray(rng.integers(1, 8192, (batch, 613)
                                        ).astype(np.int32))

        def tag(p, tb):
            return bundle.module.apply({"params": p}, tb)

        fn = jax.jit(tag)
        fn(params, toks).block_until_ready()
        dt = _bench_loop(lambda: fn(params, toks))
        out["bilstm613_tokens_per_s_per_chip"] = round(
            batch * 613 / dt, 1)
        out["bilstm613_sentences_per_s_per_chip"] = round(batch / dt, 1)
    except Exception as e:
        out["bilstm613_tokens_per_s_per_chip"] = f"error: {e}"

    # --- config 5: ViT-B/16 fine-tune step time + MFU ---
    try:
        from mmlspark_tpu.models.zoo import get_model
        from mmlspark_tpu.train.loop import TrainConfig, Trainer

        bundle = get_model("ViT_B16", num_classes=10)
        module = bundle.module
        batch = 64
        # master-free bf16 fine-tune (param_dtype) + momentum: the
        # measured round-4 winning config (PERF_NOTES) — remat and larger
        # batches both LOSE on this chip
        cfg = TrainConfig(batch_size=batch, epochs=1, optimizer="momentum",
                          learning_rate=1e-3, log_every=10**9,
                          param_dtype="bfloat16")
        trainer = Trainer(module, cfg)
        trainer.state = trainer.init_state((224, 224, 3))
        data = trainer.data_target()
        xb = jax.device_put(rng.normal(size=(batch, 224, 224, 3)
                                       ).astype(np.float32), data)
        yb = jax.device_put(rng.integers(0, 10, batch), data)
        box = {"state": trainer.state}

        def once():
            box["state"], m = trainer.step(box["state"], xb, yb)
            return m["loss"]

        float(once())  # drain compile + first step
        step_s = _bench_loop(once)
        out["vit_b16_finetune_step_ms"] = round(step_s * 1e3, 2)
        out["vit_b16_finetune_images_per_s_per_chip"] = round(
            batch / step_s / n_dev, 1)
        if peak:
            # fwd+bwd ≈ 3x forward FLOPs (XLA cost model on the fwd)
            def fwd(p, x):
                return module.apply({"params": p}, x, train=True)
            jfwd = jax.jit(fwd)
            flops = compiled_flops(jfwd, box["state"]["params"], xb)
            if flops:
                out["vit_b16_finetune_mfu"] = round(
                    3 * flops / step_s / (peak * n_dev), 4)
    except Exception as e:
        out["vit_b16_finetune_step_ms"] = f"error: {e}"

    return out


def bench_serve(jm, rng, n_total: int = 192) -> dict:
    """Serve-layer A/B: dynamic bucket-ladder batching vs batch-size-1,
    each at 1/8/64 concurrent requesters over the in-process client.

    Single-row uint8 image requests against the same ConvNet JaxModel the
    inference metrics use; the model object is shared across all runs so
    warmup compiles are paid once (the plan cache persists on the stage).
    """
    import threading

    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.serve import Client, ModelServer, ServeConfig

    imgs = rng.integers(0, 255, size=(n_total, 32 * 32 * 3)
                        ).astype(np.uint8)
    tables = [DataTable({"image": [imgs[i]]}) for i in range(n_total)]
    out: dict = {}
    for label, buckets in (("dynamic", (1, 8, 32, 128)), ("batch1", (1,))):
        for conc in (1, 8, 64):
            server = ModelServer(ServeConfig(
                buckets=buckets, max_queue=n_total + conc,
                deadline_ms=None))
            server.add_model("m", jm, example=tables[0])
            client = Client(server)
            errors: list[str] = []

            def worker(k: int) -> None:
                try:
                    for i in range(k, n_total, conc):
                        client.predict("m", tables[i], timeout=600)
                except BaseException as e:  # noqa: BLE001 — reported
                    errors.append(f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(conc)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            snap = server.stats("m").snapshot()
            server.close()
            key = f"{label}_c{conc}"
            if errors:
                out[key] = {"error": errors[0]}
                continue
            e2e = snap.get("e2e_ms") or {}
            out[key] = {
                "rows_per_s": round(n_total / wall, 1),
                "p50_ms": e2e.get("p50"),
                "p99_ms": e2e.get("p99"),
                "occupancy_mean": snap.get("batch_occupancy_mean"),
                "batches": snap.get("batches"),
            }
    return out


def bench_serve_precision(jm, rng, n_total: int = 128,
                          conc: int = 8) -> dict:
    """Serve precision A/B (round 12): the same ConvNet served f32 vs
    bf16 vs int8w through the plan-level precision pass
    (core/precision.py, docs/quantization.md) — rows/s and p99 from the
    server stats, max-abs parity vs the f32 OFFLINE transform, and the
    compute/transfer/idle split of a small traced pass per precision
    (obs device pillar), which main() archives into BENCH_OBS.json.

    On a CPU box the bf16/int8w kernels emulate (no MXU bf16 pass, no
    int8 HBM), so rows/s deltas here are labeled-regime numbers like
    Rounds 6-9 — the honest cross-regime observables are the parity and
    the weight-byte ratio; real-chip rounds read the throughput."""
    import threading

    from mmlspark_tpu import obs
    from mmlspark_tpu.core import plan as plan_lib
    from mmlspark_tpu.core.precision import (
        PrecisionPolicy, quantized_bytes,
    )
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.serve import Client, ModelServer, ServeConfig

    imgs = rng.integers(0, 255, size=(n_total, 32 * 32 * 3)
                        ).astype(np.uint8)
    tables = [DataTable({"image": [imgs[i]]}) for i in range(n_total)]
    # the f32 offline anchor (the parity-contract side of every policy)
    full = DataTable({"image": list(imgs)})
    ref = np.stack(list(jm.transform(full)["scores"]))
    out: dict = {}
    # per-model pinned tolerances (docs/quantization.md): the ConvNet's
    # logits span ~±75, so int8w's ~1.4% relative error needs an
    # absolute pin of 2.0; bf16 is BIT-identical here — the module
    # already computes in bf16, so pre-narrowed params round identically
    # and the policy is a pure wire/HBM win
    policies = {"f32": None, "bf16": "bf16",
                "int8w": {"mode": "int8w", "tolerance": 2.0}}
    for label, precision in policies.items():
        served = JaxModel(model=jm.model, input_col="image",
                          output_col="scores", minibatch_size=1024)
        server = ModelServer(ServeConfig(
            buckets=(1, 8, 32, 128), max_queue=n_total + conc,
            deadline_ms=None, precision=precision))
        try:
            server.add_model("m", served, example=tables[0])
            client = Client(server)
            errors: list[str] = []
            got: dict[int, np.ndarray] = {}

            def worker(k: int) -> None:
                try:
                    for i in range(k, n_total, conc):
                        res = client.predict("m", tables[i], timeout=600)
                        got[i] = np.asarray(res["scores"][0])
                except BaseException as e:  # noqa: BLE001 — reported
                    errors.append(f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(conc)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            snap = server.stats("m").snapshot()
            load_snap = server.snapshot()["m"]
            if errors:
                out[label] = {"error": errors[0]}
                continue
            parity = max(float(np.abs(got[i] - ref[i]).max())
                         for i in got)
            # traced pass: the compute/transfer/idle attribution per
            # precision (obs device pillar), archived in BENCH_OBS.json
            obs.registry().reset()
            obs.enable(device=True)
            try:
                for i in range(8):
                    client.predict("m", tables[i], timeout=600)
                split = obs.device_time_split()
            finally:
                obs.disable()
                obs.clear()
                obs.registry().reset()
            e2e = snap.get("e2e_ms") or {}
            rec = {
                "serve_rows_per_s": round(n_total / wall, 1),
                "serve_p99_ms": e2e.get("p99"),
                "parity_max_abs": parity,
                "occupancy_mean": snap.get("batch_occupancy_mean"),
                "device_split": split,
            }
            if precision is not None:
                rec["calibration_parity"] = load_snap.get(
                    "precision_parity")
                pol = PrecisionPolicy.parse(precision)
                rec["pinned_tolerance"] = pol.resolve_tolerance()
                seg = plan_lib.collect_segment(
                    [served], 0,
                    lambda c: plan_lib._entry_meta(full, c),
                    min_stages=1, precision=pol)
                _fn, stored = plan_lib.segment_composite(
                    seg, plan_lib._segment_mesh(seg))
                nb, fb = quantized_bytes(stored)
                rec["weight_bytes_ratio"] = round(nb / fb, 4)
            out[label] = rec
        finally:
            server.close()
    return out


def bench_serve_swap(rng, n_total: int = 160, conc: int = 8) -> dict:
    """Hot-swap under load A/B (round 13): client-observed latency with
    a version hot-swap landing mid-window vs an identical steady-state
    window, plus the dropped-request count (the zero-downtime claim,
    measured). Client-side timing, not ServerStats — the swap replaces
    the stats registry with the new version's, and the number that
    matters spans both."""
    import threading

    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.serve import Client, ModelServer, ServeConfig

    imgs = rng.integers(0, 255, size=(n_total, 32 * 32 * 3)
                        ).astype(np.uint8)
    tables = [DataTable({"image": [imgs[i]]}) for i in range(n_total)]

    def model(seed):
        return JaxModel(model=get_model("ConvNet_CIFAR10", widths=(8, 16),
                                        dense_width=32, seed=seed),
                        input_col="image", output_col="scores")

    out: dict = {}
    for label in ("steady", "swap"):
        server = ModelServer(ServeConfig(
            buckets=(1, 8, 32), max_queue=n_total + conc,
            deadline_ms=None))
        server.add_model("m", model(seed=0), example=tables[0],
                         version=1)
        client = Client(server)
        lat: list[float] = []
        errors: list[str] = []
        lock = threading.Lock()

        def worker(k: int) -> None:
            # per-REQUEST error capture: one failure must count as one
            # dropped request and the rest of the window still run —
            # aborting the worker would shrink the sample and
            # under-report the very outage this A/B exists to measure
            for i in range(k, n_total, conc):
                t0 = time.perf_counter()
                try:
                    client.predict("m", tables[i], timeout=600)
                except BaseException as e:  # noqa: BLE001 — counted
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    continue
                dt = time.perf_counter() - t0
                with lock:
                    lat.append(dt)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        swap_wall_ms = None
        if label == "swap":
            # land the swap inside the window: v2 loads + warms its
            # ladder while v1 serves, then the name flips atomically
            time.sleep(0.05)
            s0 = time.perf_counter()
            server.add_model("m", model(seed=1), example=tables[0],
                             version=2)
            swap_wall_ms = round((time.perf_counter() - s0) * 1e3, 1)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        server.close()
        entry = {
            "rows_per_s": round(len(lat) / wall, 1),
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
            "dropped": len(errors),
        }
        if swap_wall_ms is not None:
            entry["swap_wall_ms"] = swap_wall_ms
        if errors:
            entry["first_error"] = errors[0]
        out[label] = entry
    steady99, swap99 = out["steady"]["p99_ms"], out["swap"]["p99_ms"]
    out["p99_ratio_swap_vs_steady"] = (
        round(swap99 / steady99, 3) if steady99 else None)
    return out


def bench_serve_generate(rng, n_req: int = 32, max_new: int = 16) -> dict:
    """Token-serving bench (round 18): a streaming generate burst
    through the continuous-batching engine (serve/generate.py) — wall
    tokens/s, TTFT p50/p99 and ITL p99 from the engine's ServerStats,
    mean slot occupancy, and the compiled-program count against the
    ``len(prefill_buckets) + 1`` budget.

    A small causal TransformerTagger on CPU is a labeled-regime number
    like the precision A/B — the cross-regime observables are the
    program budget and occupancy; real-chip rounds read the
    throughput/latency. Warmup goes through ``generate_oneshot`` (the
    same compiled programs, no stats), so the burst percentiles never
    include compile time."""
    import jax

    from mmlspark_tpu.models.sequence import TransformerTagger
    from mmlspark_tpu.serve import (
        Client, GenerateConfig, ModelServer, ServeConfig,
    )

    vocab, t_max = 128, 128
    model = TransformerTagger(vocab_size=vocab, embed_dim=32, num_heads=2,
                              num_layers=2, mlp_dim=64, num_tags=vocab,
                              max_len=t_max, causal=True)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    cfg = GenerateConfig(slots=8, t_max=t_max, prefill_buckets=(8, 32),
                         prefill_rows=4, max_new_tokens=max_new,
                         max_queue=n_req + 8)
    prompts = [[int(t) for t in rng.integers(1, vocab,
                                             int(rng.integers(4, 30)))]
               for _ in range(n_req)]
    server = ModelServer(ServeConfig())
    try:
        server.add_generator("lm", model, params, config=cfg)
        for blen in cfg.prefill_buckets:  # warm the ladder + decode
            server.generate_oneshot(
                "lm", [int(t) for t in rng.integers(1, vocab, blen - 1)],
                max_new_tokens=2)
        client = Client(server)
        t0 = time.perf_counter()
        streams = [client.generate("lm", p, stream=True) for p in prompts]
        toks = [st.result(timeout=600) for st in streams]
        wall = time.perf_counter() - t0
        snap = server.snapshot()["lm"]
        programs = snap["programs_compiled"]
    finally:
        server.close()
    n_tokens = sum(len(t) for t in toks)
    ttft = snap.get("ttft_ms") or {}
    itl = snap.get("itl_ms") or {}
    return {
        "requests": n_req,
        "max_new_tokens": max_new,
        "tokens": n_tokens,
        "tokens_per_s": round(n_tokens / wall, 1),
        "ttft_p50_ms": ttft.get("p50"),
        "ttft_p99_ms": ttft.get("p99"),
        "itl_p99_ms": itl.get("p99"),
        "slot_occupancy_mean": snap.get("slot_occupancy_mean"),
        "decode_steps": snap.get("decode_steps"),
        "programs_compiled": programs,
        "program_budget": len(cfg.prefill_buckets) + 1,
    }


def bench_serve_sharded(jm, rng, n_total: int = 192,
                        conc: int = 8) -> dict:
    """Sharded-serving scaling A/B: one chip (``dp=1``) vs DP-replica
    fan-out over every local chip (``dp=N``), same request stream, same
    bucket ladder, ``conc`` concurrent requesters.

    On real multi-chip hosts the N-replica run multiplies the Round-8
    single-chip numbers (each replica owns its chip, params uploaded once
    per replica); on a single-device (or virtual-CPU) box the A/B
    degenerates and the honest scaling evidence is the latency-bound
    dryrun gate (``tools/perf_smoke.py check_serve_sharded``) — the
    record labels which regime it measured via ``n_devices``.
    """
    import threading

    import jax

    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.serve import Client, ModelServer, ServeConfig

    n_dev = len(jax.local_devices())
    meshes = [("dp1", "dp=1")]
    if n_dev > 1:
        meshes.append((f"dp{n_dev}", f"dp={n_dev}"))
    imgs = rng.integers(0, 255, size=(n_total, 32 * 32 * 3)
                        ).astype(np.uint8)
    tables = [DataTable({"image": [imgs[i]]}) for i in range(n_total)]
    out: dict = {"n_devices": n_dev}
    for label, mesh in meshes:
        server = ModelServer(ServeConfig(
            buckets=(1, 8, 32, 128), max_queue=n_total + conc,
            deadline_ms=None, mesh=mesh))
        server.add_model("m", jm, example=tables[0])
        client = Client(server)
        errors: list[str] = []

        def worker(k: int) -> None:
            try:
                for i in range(k, n_total, conc):
                    client.predict("m", tables[i], timeout=600)
            except BaseException as e:  # noqa: BLE001 — reported
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        snap = server.stats("m").snapshot()
        programs = server.compiled_programs("m")
        server.close()
        if errors:
            out[label] = {"error": errors[0]}
            continue
        e2e = snap.get("e2e_ms") or {}
        out[label] = {
            "rows_per_s": round(n_total / wall, 1),
            "p99_ms": e2e.get("p99"),
            "batches": snap.get("batches"),
            "programs_compiled": programs,
            "replica_batches": {k: v.get("batches")
                                for k, v in snap["replicas"].items()},
        }
    first, last = out[meshes[0][0]], out[meshes[-1][0]]
    if (len(meshes) > 1 and isinstance(first.get("rows_per_s"), float)
            and isinstance(last.get("rows_per_s"), float)
            and first["rows_per_s"]):
        out["speedup"] = round(last["rows_per_s"] / first["rows_per_s"],
                               2)
    return out


def bench_serve_load_wall(rng) -> dict:
    """Model-load wall A/B through the persistent AOT compile cache
    (core/compile_cache.py, docs/serving.md §compile cache): the same
    ConvNet loaded twice against one cache dir — cold (empty cache:
    every bucket program XLA-compiles and publishes) vs warm (every
    program deserializes). Fresh bundle/model objects per load, so the
    warm pass cannot ride the in-process plan cache; the cross-PROCESS
    version of this claim is gated in perf_smoke check_compile_cache.
    Walls include analyzer validation + full-ladder warmup — the number
    a fleet restart actually waits on."""
    import shutil
    import tempfile

    from mmlspark_tpu.core import compile_cache as cc
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model
    from mmlspark_tpu.serve import ModelServer, ServeConfig

    img = rng.integers(0, 255, size=(32 * 32 * 3,)).astype(np.uint8)
    tmp = tempfile.mkdtemp(prefix="bench-compile-cache-")
    out: dict = {}
    try:
        for label in ("cold", "warm"):
            cc.reset()
            bundle = get_model("ConvNet_CIFAR10")
            jm = JaxModel(model=bundle, input_col="image",
                          output_col="scores")
            server = ModelServer(ServeConfig(
                buckets=(1, 8, 32, 128), deadline_ms=None,
                compile_cache=tmp))
            t0 = time.perf_counter()
            server.add_model("m", jm,
                             example=DataTable({"image": [img]}))
            wall = time.perf_counter() - t0
            stats = dict(cc.active().stats)
            server.close()
            out[label] = {
                "load_wall_s": round(wall, 3),
                "hits": stats["hits"],
                "misses": stats["misses"],
                "puts": stats["puts"],
                "xla_compiles": stats["compiles"],
                "deserialize_ms": round(stats["load_ms"], 1),
            }
        out["cache_bytes"] = stats["bytes"]
        cold_w = out["cold"]["load_wall_s"]
        if cold_w:
            out["speedup"] = round(cold_w / max(
                out["warm"]["load_wall_s"], 1e-9), 2)
    finally:
        cc.reset()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_serve_fleet(rng, n_total: int = 64, conc: int = 8) -> dict:
    """Fleet-tier serving A/B (round 19): the same request stream pushed
    through the router (serve/fleet/) at 1 supervised backend, then at 2
    after a ``scale_up``, then a 2-backend burst with one backend
    kill -9'd mid-burst — wall rows/s per fleet size plus the
    client-observed p99 across the kill (failover pays the re-route
    INSIDE the request; the kill burst must finish with zero errors).

    Backends are separate processes sharing this box's cores, so on a
    CPU box the 2-backend rows/s is a labeled-regime number like the
    sharded A/B — on real multi-chip hosts each backend owns its chips
    and the A/B multiplies. The cross-regime observables are the zero
    kill errors and the bounded kill p99."""
    import os
    import shutil
    import signal as _signal
    import tempfile
    import threading
    import urllib.request

    from mmlspark_tpu.serve.fleet import (
        BackendPool, FleetConfig, FleetRouter, ScalePolicy,
        ServeSupervisor,
    )
    from mmlspark_tpu.serve.fleet.worker import MODEL_NAME, selftest_rows
    from mmlspark_tpu.train.service import RecoveryPolicy

    tmp = tempfile.mkdtemp(prefix="bench-serve-fleet-")
    rows = selftest_rows(8)
    body = json.dumps({"rows": [{"image": r.tolist()} for r in rows],
                       "dtype": "uint8"}).encode()
    pool = BackendPool()
    sup = ServeSupervisor(FleetConfig(
        service_dir=os.path.join(tmp, "fleet"), initial_backends=1,
        compile_cache=os.path.join(tmp, "cache"),
        policy=RecoveryPolicy(max_restarts=2,
                              rescale_on_exhausted=False,
                              preempt_exit_codes=()),
        # manual scaling only: the bench drives fleet size itself
        scale=ScalePolicy(burn_sustain_s=3600.0, idle_sustain_s=3600.0,
                          min_backends=1, max_backends=2),
        worker_obs=False, worker_fleet=False), pool=pool)
    router = FleetRouter(pool)

    def wait_up(n, timeout=240.0):
        deadline = time.perf_counter() + timeout
        while pool.up_count() < n:
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"fleet never reached {n} backends: "
                    f"{pool.snapshot()}")
            time.sleep(0.2)

    def burst(kill_pid=None):
        """n_total requests over conc threads; optionally SIGKILL a
        backend once ~25% of the stream is underway. Returns
        (rows_per_s, latencies_ms, errors)."""
        lat_ms: list[float] = []
        errors: list[str] = []
        done = [0]
        lock = threading.Lock()
        host, port = router.address
        url = f"http://{host}:{port}/v1/models/{MODEL_NAME}:predict"

        def one():
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
            return (time.perf_counter() - t0) * 1e3

        def worker(k):
            for _ in range(k, n_total, conc):
                try:
                    ms = one()
                    with lock:
                        lat_ms.append(ms)
                        done[0] += 1
                except Exception as e:  # noqa: BLE001 — reported
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        if kill_pid is not None:
            while True:
                with lock:
                    if done[0] >= n_total // 4 or errors:
                        break
                time.sleep(0.005)
            os.kill(kill_pid, _signal.SIGKILL)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return round(n_total * len(rows) / wall, 1), lat_ms, errors

    out: dict = {"requests": n_total, "rows_per_request": len(rows)}

    def record(label, rps, lat_ms, errors):
        out[label] = {
            "rows_per_s": rps,
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 1)
            if lat_ms else None,
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 1)
            if lat_ms else None,
            "errors": len(errors),
        }
        if errors:
            out[label]["first_error"] = errors[0]

    try:
        sup.start()
        router.start()
        wait_up(1)
        burst()  # warm the ladder through the router
        record("fleet1", *burst())
        sup.scale_up()
        wait_up(2)
        record("fleet2", *burst())
        if isinstance(out["fleet1"]["rows_per_s"], float) \
                and out["fleet1"]["rows_per_s"]:
            out["speedup"] = round(out["fleet2"]["rows_per_s"]
                                   / out["fleet1"]["rows_per_s"], 2)
        victim = next(iter(sup._backends.values()))
        record("kill", *burst(kill_pid=victim.proc.pid))
    finally:
        router.close()
        sup.close()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_deploy(rng) -> dict:
    """Checkpoint→serving wall through the lifecycle deployer
    (mmlspark_tpu/lifecycle, docs/lifecycle.md): the time from
    ``start_rollout`` on an already-published version to PROMOTED —
    shadow warmup, canary ramp under a trickle of live traffic, repo
    ``CURRENT`` flip — cold (empty compile cache: every candidate
    bucket program XLA-compiles during the shadow deploy) vs warm (the
    same rollout against the cache the cold pass populated). Fresh
    server/bundle objects per pass, same repo artifacts; bench_check
    gates warm <= cold WITHIN this line — absolute deploy walls are box
    weather, the cache either cuts the candidate warmup or it doesn't."""
    import shutil
    import tempfile

    from mmlspark_tpu.core import compile_cache as cc
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.lifecycle import Deployer, RolloutPolicy, ServerTarget
    from mmlspark_tpu.models.bundle import ModelBundle
    from mmlspark_tpu.models.repo import ModelRepo
    from mmlspark_tpu.models.zoo import MLP
    from mmlspark_tpu.serve import Client, ModelServer, ServeConfig

    import jax

    d_in = 32
    module = MLP(features=(64, 64), num_outputs=8)
    rows = rng.normal(size=(8, d_in)).astype(np.float32)
    example = DataTable({"input": list(rows[:1])})
    tmp = tempfile.mkdtemp(prefix="bench-deploy-")
    out: dict = {}
    try:
        repo = ModelRepo(f"{tmp}/repo")
        for seed in (0, 1):
            params = module.init(
                jax.random.PRNGKey(seed),
                np.zeros((1, d_in), np.float32))["params"]
            repo.publish("m", ModelBundle(
                module=module,
                params=jax.tree_util.tree_map(np.asarray, params),
                input_spec=(d_in,), output_names=("logits",), name="m"))
        for label in ("cold", "warm"):
            cc.reset()
            repo.set_current("m", 1)
            server = ModelServer(ServeConfig(
                buckets=(1, 8), deadline_ms=None,
                compile_cache=f"{tmp}/cc"))
            server.add_model_from_repo(repo, "m", version=1,
                                       example=example)
            client = Client(server)
            deployer = Deployer(
                f"{tmp}/lifecycle_{label}", repo,
                ServerTarget(server, "m", example=example),
                policy=RolloutPolicy(advance_after=1))
            t0 = time.perf_counter()
            rollout = deployer.start_rollout("m", version=2)
            while not rollout.done:
                # the trickle of live traffic every ramp stage needs
                # for a verdict (no canary evidence ⇒ the policy holds)
                for _ in range(2):
                    client.predict("m", DataTable({"input": list(rows)}),
                                   timeout=30)
                deployer.tick(rollout)
            wall = time.perf_counter() - t0
            stats = dict(cc.active().stats)
            server.close()
            out[label] = {
                "deploy_wall_s": round(wall, 3),
                "outcome": rollout.outcome,
                "ticks": rollout.ledger.ticks,
                "xla_compiles": stats["compiles"],
                "cache_hits": stats["hits"],
            }
        cold_w = out["cold"]["deploy_wall_s"]
        if cold_w:
            out["speedup"] = round(cold_w / max(
                out["warm"]["deploy_wall_s"], 1e-9), 2)
    finally:
        cc.reset()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def main() -> int:
    import jax

    from mmlspark_tpu.models.zoo import ConvNetCifar
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    batch = 1024  # large enough that compute dominates dispatch latency
    module = ConvNetCifar()
    cfg = TrainConfig(batch_size=batch, epochs=1, optimizer="momentum",
                      learning_rate=0.01, log_every=10**9)
    trainer = Trainer(module, cfg)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=batch)

    trainer.state = trainer.init_state(x.shape[1:])
    # batches must be committed to the dp sharding: the jit infers shardings
    # from its args, so an uncommitted numpy batch would replicate (each chip
    # redundantly computing the full batch) and skew per-chip throughput.
    # (On a 1-device mesh data_target is the bare device — plain transfers.)
    data = trainer.data_target()
    x = jax.device_put(x, data)
    y = jax.device_put(y, data)
    # warmup/compile; the scalar fetch (not block_until_ready, which is not
    # a reliable barrier through remote-device tunnels) drains the pipeline
    state, m = trainer.step(trainer.state, x, y)
    float(m["loss"])

    box = {"state": state}

    def once():
        box["state"], m = trainer.step(box["state"], x, y)
        return m["loss"]

    # RTT-cancelling paired windows (see _bench_loop) — at round 5's
    # ~85-110 ms fetch RTT a single 100-step window still understated
    # throughput ~9%
    step_dt = _bench_loop(once, steps=50)

    n_dev = jax.device_count()
    images_per_s_per_chip = batch / step_dt / n_dev
    # fwd + bwd ≈ 3x forward FLOPs
    step_flops = 3 * conv_flops_per_example(module, (32, 32, 3)) * batch
    peak = peak_flops_per_chip()
    device = jax.devices()[0].device_kind
    if peak is None:
        vs_baseline = None  # unknown hardware: MFU ratio would be garbage
    else:
        mfu = step_flops / step_dt / (peak * n_dev)
        vs_baseline = round(mfu / 0.60, 4)

    # transfer calibration: the inference/bridge numbers are dominated by
    # the host→device link (through the driver's tunnel its incompressible
    # bandwidth swings run-to-run by >2x — r2 measured 14.8k img/s against
    # r3's 6.9k with byte-identical hot-path code). Measuring the link in
    # the same process makes every round's number self-attributing:
    # compute-vs-transfer splits cleanly instead of reading as a code
    # regression. (PERF_NOTES round 4.)
    # device-health calibration: an 8k³ bf16 matmul runs at ≥95% of any
    # healthy TPU's nominal peak, and the scalar-fetch RTT is the additive
    # artifact every timed window fights. Recording both makes each
    # round's MFU numbers self-attributing: a low MFU with a low
    # mxu_matmul_tf_s is a degraded chip/tunnel regime, not a code
    # regression (PERF_NOTES round 5).
    mxu_tf_s = None
    rtt_ms = None
    try:
        import jax.numpy as jnp
        fetch = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32)))
        t = []
        s = jnp.zeros((1,), jnp.float32)
        float(fetch(s))
        for _ in range(5):
            t0 = time.perf_counter()
            float(fetch(s))
            t.append(time.perf_counter() - t0)
        rtt_ms = round(min(t) * 1e3, 1)
        mm = jnp.asarray(rng.standard_normal((8192, 8192), np.float32),
                         jnp.bfloat16)
        g = jax.jit(lambda a, b: a @ b)
        mdt = _bench_loop(lambda: g(mm, mm), steps=5)
        mxu_tf_s = round(2 * 8192**3 / mdt / 1e12, 1)
    except Exception as e:
        mxu_tf_s = f"error: {e}"

    tunnel_mb_s = None
    try:
        import jax
        import jax.numpy as jnp
        payload = rng.integers(0, 256, size=24 << 20).astype(np.uint8)
        fetch = jax.jit(lambda a: jnp.sum(a.astype(jnp.uint32)))
        dev0 = jax.devices()[0]
        int(fetch(jax.device_put(payload[: 1 << 16], dev0)))  # warm
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            int(fetch(jax.device_put(payload, dev0)))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        tunnel_mb_s = round(len(payload) / best / 2**20, 1)
    except Exception as e:
        tunnel_mb_s = f"error: {e}"

    # second BASELINE.json metric: Spark→TPU batch p50 latency through the
    # Arrow offload bridge (partition → padded device batch → scored rows),
    # plus raw batched-inference throughput (notebook-301 scoring path)
    bridge_p50 = None
    infer_ips = None
    infer_compute_ips = None
    table = None
    jm = None
    try:
        from mmlspark_tpu.data.table import DataTable
        from mmlspark_tpu.models.jax_model import JaxModel
        from mmlspark_tpu.models.zoo import get_model

        bundle = get_model("ConvNet_CIFAR10")
        jm = JaxModel(model=bundle, input_col="image", output_col="scores",
                      minibatch_size=1024)
        n_inf = 8192
        # decoded image bytes are uint8 — ship them thin, upcast on device
        imgs = rng.integers(0, 255, size=(n_inf, 32, 32, 3)
                            ).astype(np.uint8)
        table = DataTable({"image": list(imgs.reshape(n_inf, -1))})
        jm.transform(table)  # compile + param upload
        infer_dt = None
        for _ in range(2):  # best-of-2: tunnel throughput is noisy
            t0 = time.perf_counter()
            jm.transform(table)
            dt_i = time.perf_counter() - t0
            infer_dt = dt_i if infer_dt is None else min(infer_dt, dt_i)
        infer_ips = round(n_inf / infer_dt / n_dev, 1)
    except Exception as e:  # best-effort metric; label failures accurately
        infer_ips = f"error: {e}"

    try:
        if jm is None or table is None:
            raise RuntimeError("inference setup failed")
        # compute-only companion number: the same compiled forward with the
        # batch already device-resident. Tunnel-independent, so a drop in
        # infer_ips with a steady infer_compute_ips is link drift, not code.
        # (Its own try: a failure here must label THIS metric, not clobber
        # an already-measured infer_ips.)
        fn, dev_params, data, _dp = jm._compiled_apply(
            jm.model, jm._resolve_node(jm.model))
        mb = 1024
        imgs_c = rng.integers(0, 255, size=(mb, 32, 32, 3)).astype(np.uint8)
        dev_batch = jax.device_put(imgs_c, data)
        fn(dev_params, dev_batch).block_until_ready()
        cdt = _bench_loop(lambda: fn(dev_params, dev_batch))
        infer_compute_ips = round(mb / cdt / n_dev, 1)
    except Exception as e:
        infer_compute_ips = f"error: {e}"

    bridge_decomp: dict | None = None
    bridge_rows_s = None
    try:
        if table is None or jm is None:
            raise RuntimeError("inference setup failed, bridge skipped")
        from mmlspark_tpu.bridge import ArrowBatchBridge
        from mmlspark_tpu.bridge.offload import stream_table

        small = table.take(np.arange(2048))
        # warmup with the SAME chunking so the timed pass never compiles
        warmup = ArrowBatchBridge(jm)
        for _ in warmup.process(stream_table(small, 128)):
            pass
        # 16 timed batches: a p50 over 4 samples swung ±60% run to run.
        # workers=2 (the spark_transform default) overlaps marshal with
        # the device round-trip; per-batch p50 stays RTT-floored through
        # the tunnel but wall-clock throughput (rows/s) reflects overlap
        bridge2 = ArrowBatchBridge(jm)
        t0 = time.perf_counter()
        for _ in bridge2.process(stream_table(small, 128)):
            pass
        bridge_rows_s = round(len(small) / (time.perf_counter() - t0), 1)
        bridge_p50 = round(bridge2.p50_latency_ms(), 2)
        d = bridge2.p50_decomposition()
        bridge_decomp = {k: round(v, 2) for k, v in d.items()} if d else None
    except Exception as e:  # bridge metric is best-effort in the bench
        bridge_p50 = f"error: {e}"

    # fused-vs-unfused pipeline execution (round 6): the canonical 3-stage
    # image pipeline (resize → unroll → score) through the pipeline planner
    # (ONE compiled program, one H2D upload of the raw uint8 batch + one
    # async fetch per minibatch) against the stage-by-stage host path. The
    # crossing counts make the fusion visible independently of link drift.
    pipe_rows_s = None
    pipe_rows_s_unfused = None
    pipe_crossings = None
    obs_snapshot = None
    try:
        if jm is None:
            raise RuntimeError("inference setup failed, pipeline skipped")
        from mmlspark_tpu.core import plan as plan_lib
        from mmlspark_tpu.core.pipeline import PipelineModel
        from mmlspark_tpu.core.schema import make_image
        from mmlspark_tpu.data.table import DataTable
        from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage

        n_pipe = 2048
        src = rng.integers(0, 255, size=(n_pipe, 48, 48, 3)).astype(np.uint8)
        ptable = DataTable({"image": [make_image(f"i{k}", src[k])
                                      for k in range(n_pipe)]})
        stages = [
            ImageTransformer().resize(32, 32),
            UnrollImage(input_col="image", output_col="image_vec"),
            JaxModel(model=jm.model, input_col="image_vec",
                     output_col="scores", minibatch_size=1024),
        ]
        pm = PipelineModel(stages)
        # warm both paths at the SAME minibatch shape so the timed passes
        # never compile (1024 rows → one full-size minibatch)
        warm = ptable.take(np.arange(1024))
        pm.transform(warm)
        cur = warm
        for s in stages:
            cur = s.transform(cur)
        # the timed fused pass runs UNTRACED (tracer-on would bias the
        # fused-vs-unfused A/B with span/counter work the baseline never
        # pays); a separate small traced pass below cross-checks that the
        # obs registry reads EXACTLY what the seam-patching counter reads
        # (one substrate — docs/observability.md), so every PERF_NOTES
        # round double-checks the numbers the runtime exports
        with plan_lib.count_crossings() as cnt:
            t0 = time.perf_counter()
            pm.transform(ptable)
            fused_dt = time.perf_counter() - t0
        pipe_crossings = {"fused_h2d": cnt.uploads, "fused_d2h": cnt.fetches,
                          "fused_h2d_mb": round(cnt.upload_bytes / 2**20, 2)}
        from mmlspark_tpu import obs
        obs.registry().reset()
        # device=True: the traced pass also captures per-segment compile
        # cost + XLA cost/memory gauges (plan.segment.*) and the
        # compute/transfer/idle split — the attribution behind any
        # "input-bound" or HBM claim a PERF_NOTES round makes
        obs.enable(device=True)
        try:
            with plan_lib.count_crossings() as chk:
                pm.transform(warm)  # untimed: the obs-agreement pass
        finally:
            obs.disable()
        # keep the WHOLE registry view of the traced pass: it is
        # archived next to the bench record (BENCH_OBS.json) so the
        # bench trajectory accumulates comparable telemetry — same
        # snapshot schema as the /metrics endpoint
        obs_snapshot = obs.registry().snapshot()
        obs_counters = obs_snapshot["counters"]
        device_split = obs.device_time_split()
        obs.clear()
        obs.registry().reset()
        obs.device.reset()
        pipe_crossings["obs_agrees"] = (
            obs_counters.get("plan.h2d_uploads", 0) == chk.uploads
            and obs_counters.get("plan.d2h_fetches", 0) == chk.fetches
            and obs_counters.get("plan.h2d_bytes", 0) == chk.upload_bytes)
        pipe_crossings["device_split"] = device_split
        pipe_crossings["segment_gauges"] = {
            k: v for k, v in obs_snapshot["gauges"].items()
            if k.startswith("plan.segment.")}
        with plan_lib.count_crossings() as cnt:
            t0 = time.perf_counter()
            cur = ptable
            for s in stages:
                cur = s.transform(cur)
            unfused_dt = time.perf_counter() - t0
        pipe_crossings["unfused_h2d"] = cnt.uploads
        pipe_crossings["unfused_d2h"] = cnt.fetches
        pipe_crossings["unfused_h2d_mb"] = round(cnt.upload_bytes / 2**20, 2)
        pipe_rows_s = round(n_pipe / fused_dt, 1)
        pipe_rows_s_unfused = round(n_pipe / unfused_dt, 1)
    except Exception as e:  # best-effort metric; label failures accurately
        pipe_rows_s = f"error: {e}"

    # train input pipeline (round 7): prefetch on/off A/B on the canonical
    # CIFAR train config. With prefetch the batch gather + H2D commit run
    # on a background thread up to prefetch_depth steps ahead
    # (train/input.DeviceLoader), so steady-state step wall-clock is
    # max(H2D, compute) instead of the sum; the uint8 batches ship thin
    # and cast/normalize inside the jitted step. Numerics are bit-identical
    # across the A/B (asserted in tests/test_train_input.py); the wait
    # fractions make the split self-attributing under link drift
    train_ab: dict | None = None
    try:
        n_tr, bs_tr = 2048, 256
        x_tr = rng.integers(0, 255, size=(n_tr, 32, 32, 3)).astype(np.uint8)
        y_tr = rng.integers(0, 10, size=n_tr).astype(np.int64)
        train_ab = {}
        for label, depth in (("prefetch", 2), ("sync", 0)):
            cfg_tr = TrainConfig(batch_size=bs_tr, epochs=1,
                                 optimizer="momentum", learning_rate=0.01,
                                 log_every=10**9, prefetch_depth=depth,
                                 seed=0)
            tr = Trainer(ConvNetCifar(), cfg_tr)
            # warm pass compiles step_masked at the timed batch shape
            tr.fit_arrays(x_tr[:2 * bs_tr], y_tr[:2 * bs_tr])
            t0 = time.perf_counter()
            tr.fit_arrays(x_tr, y_tr)
            dt = time.perf_counter() - t0
            s = tr.input_stats or {}
            train_ab[label] = {
                "images_per_s_per_chip": round(n_tr / dt / n_dev, 1),
                "input_bound_fraction": s.get("input_bound_fraction"),
                "input_wait_s": s.get("input_wait_s"),
                "step_s": s.get("step_s"),
                "assemble_s": s.get("assemble_s"),
                "commit_s": s.get("commit_s"),
                "committed_ahead_max": s.get("committed_ahead_max"),
            }
    except Exception as e:  # best-effort metric; label failures accurately
        train_ab = {"error": f"{type(e).__name__}: {e}"}

    # on-device preprocessing (round 10): host-preprocessed f32 batches
    # vs thin uint8 + DevicePreprocess fused into the jitted step, at
    # full augmentation (pad-crop/flip/brightness/contrast). Both runs
    # execute the SAME stochastic stages on device (draws fold from the
    # global step), so the A/B isolates the wire form: f32 final-width
    # pixels vs uint8 source pixels with geometry replayed in-step. The
    # crossing byte counts (train_commit seam) make the cut visible
    # independently of link drift
    train_pp_ab: dict | None = None
    try:
        from mmlspark_tpu.core import plan as plan_lib2
        from mmlspark_tpu.train.preprocess import (
            DevicePreprocess, host_preprocess,
        )
        spec = DevicePreprocess(crop_pad=4, flip_lr=True, brightness=0.1,
                                contrast=(0.9, 1.1))
        n_pp, bs_pp = 2048, 256
        x_pp = rng.integers(0, 255, size=(n_pp, 32, 32, 3)
                            ).astype(np.uint8)
        y_pp = rng.integers(0, 10, size=n_pp).astype(np.int64)
        train_pp_ab = {}
        for label, data in (("device_thin", x_pp),
                            ("host_f32",
                             host_preprocess(spec, x_pp, 1.0 / 255.0))):
            cfg_pp = TrainConfig(batch_size=bs_pp, epochs=1,
                                 optimizer="momentum", learning_rate=0.01,
                                 log_every=10**9, prefetch_depth=2,
                                 preprocess=spec, seed=0)
            tr = Trainer(ConvNetCifar(), cfg_pp)
            tr.fit_arrays(data[:2 * bs_pp], y_pp[:2 * bs_pp])  # warm
            with plan_lib2.count_crossings() as cnt:
                t0 = time.perf_counter()
                tr.fit_arrays(data, y_pp)
                dt = time.perf_counter() - t0
            s = tr.input_stats or {}
            train_pp_ab[label] = {
                "images_per_s_per_chip": round(n_pp / dt / n_dev, 1),
                "h2d_mb": round(cnt.upload_bytes / 2**20, 2),
                "wire_mb": s.get("wire_mb"),
                "input_bound_fraction": s.get("input_bound_fraction"),
            }
        thin_mb = train_pp_ab["device_thin"]["h2d_mb"]
        host_mb = train_pp_ab["host_f32"]["h2d_mb"]
        train_pp_ab["h2d_reduction"] = (round(host_mb / thin_mb, 2)
                                        if thin_mb else None)
    except Exception as e:  # best-effort metric; label failures accurately
        train_pp_ab = {"error": f"{type(e).__name__}: {e}"}

    # online serving (round 8): the dynamic-batching model server through
    # the in-process client at 1/8/64 concurrent requesters, A/B dynamic
    # batching (the bucket ladder) vs batch-size-1 (buckets=(1,): every
    # request its own dispatch). rows/s is wall-clock completion rate,
    # p99 the per-request end-to-end latency from ServerStats — under
    # concurrency the ladder converts queue depth into batch occupancy
    # instead of a serialized dispatch train
    serve_ab: dict | None = None
    try:
        if jm is None:
            raise RuntimeError("inference setup failed, serve skipped")
        serve_ab = bench_serve(jm, rng)
    except Exception as e:  # best-effort metric; label failures accurately
        serve_ab = {"error": f"{type(e).__name__}: {e}"}

    # sharded serving (round 9): dp=1 vs dp=N replica fan-out — every
    # added chip should multiply the round-8 per-chip serve numbers
    # (replica scheduler + per-replica param upload; docs/serving.md)
    serve_sharded: dict | None = None
    try:
        if jm is None:
            raise RuntimeError("inference setup failed, serve skipped")
        serve_sharded = bench_serve_sharded(jm, rng)
    except Exception as e:  # best-effort metric; label failures accurately
        serve_sharded = {"error": f"{type(e).__name__}: {e}"}

    # serve precision A/B (round 12): f32 vs bf16 vs int8w through the
    # plan-level precision pass — parity vs the f32 offline transform,
    # rows/s + p99 per policy, and the traced compute/transfer/idle
    # split per precision (archived in BENCH_OBS.json)
    serve_precision: dict | None = None
    try:
        if jm is None:
            raise RuntimeError("inference setup failed, serve skipped")
        serve_precision = bench_serve_precision(jm, rng)
    except Exception as e:  # best-effort metric; label failures accurately
        serve_precision = {"error": f"{type(e).__name__}: {e}"}

    # hot-swap under load (round 13): a version flip mid-window vs an
    # identical steady window — client-observed p99 and the
    # dropped-request count (the zero-downtime lifecycle, measured)
    serve_swap: dict | None = None
    try:
        if jm is None:
            raise RuntimeError("inference setup failed, serve skipped")
        serve_swap = bench_serve_swap(rng)
    except Exception as e:  # best-effort metric; label failures accurately
        serve_swap = {"error": f"{type(e).__name__}: {e}"}

    # token serving (round 18): streaming generate burst through the
    # continuous-batching engine — tokens/s, TTFT/ITL percentiles, slot
    # occupancy, and the compiled-program budget (docs/serving.md
    # §token streaming)
    serve_generate: dict | None = None
    try:
        serve_generate = bench_serve_generate(rng)
    except Exception as e:  # best-effort metric; label failures accurately
        serve_generate = {"error": f"{type(e).__name__}: {e}"}

    # compile-cache load-wall A/B (round 18): cold (compile + publish)
    # vs warm (deserialize) model load against one cache dir — the
    # restart wall a fleet actually pays (docs/serving.md §compile
    # cache); bench_check gates warm <= cold WITHIN this line, never
    # across rounds (absolute load walls are box weather)
    serve_load_wall: dict | None = None
    try:
        serve_load_wall = bench_serve_load_wall(rng)
    except Exception as e:  # best-effort metric; label failures accurately
        serve_load_wall = {"error": f"{type(e).__name__}: {e}"}

    # fleet serving (round 19): 1-vs-2 supervised backend processes
    # behind the router, plus client-observed p99 across an induced
    # kill -9 mid-burst — the failover cost as the client pays it
    # (docs/serving.md §fleet tier)
    serve_fleet: dict | None = None
    try:
        serve_fleet = bench_serve_fleet(rng)
    except Exception as e:  # best-effort metric; label failures accurately
        serve_fleet = {"error": f"{type(e).__name__}: {e}"}

    # continuous deployment (round 20): checkpoint→serving wall through
    # the lifecycle deployer, cold vs compile-cache-warm candidate
    # warmup — the promotion latency a fleet rollout actually pays
    # (docs/lifecycle.md); bench_check gates warm <= cold within-line
    deploy: dict | None = None
    try:
        deploy = bench_deploy(rng)
    except Exception as e:  # best-effort metric; label failures accurately
        deploy = {"error": f"{type(e).__name__}: {e}"}

    # BASELINE configs 3-5 (flagship models); skip with BENCH_FAST=1
    import os
    extra: dict = {}
    if os.environ.get("BENCH_FAST", "0") == "0":
        extra = bench_flagship_models(rng, n_dev, peak)

    # archive the obs registry snapshot of the traced fused pass next to
    # the bench record: BENCH_r*.json captures only stdout, so this file
    # is where the bench trajectory accumulates comparable telemetry
    # (crossing/byte/compile counters and span histograms, in the same
    # schema the /metrics endpoint serves). Best-effort — a read-only
    # checkout must not fail the bench
    obs_archive = None
    if obs_snapshot is not None:
        try:
            obs_archive = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_OBS.json")
            with open(obs_archive, "w", encoding="utf-8") as fh:
                json.dump({
                    "metric": METRIC_NAME,
                    "device": device,
                    "obs_registry": obs_snapshot,
                    "pipeline_crossings": pipe_crossings,
                    "serve_stats": {
                        k: v for k, v in (serve_ab or {}).items()
                        if isinstance(v, dict)},
                    "serve_sharded": serve_sharded,
                    # compute/transfer/idle split per serving precision
                    # (the obs device pillar's traced pass per policy)
                    "serve_precision": serve_precision,
                }, fh, indent=2, default=str)
        except OSError:
            obs_archive = None

    line = {
        "metric": METRIC_NAME,
        "value": round(images_per_s_per_chip, 1),
        "unit": METRIC_UNIT,
        "vs_baseline": vs_baseline,
        "device": device,
        "bridge_batch_p50_ms": bridge_p50,
        "bridge_p50_marshal_ms": (bridge_decomp or {}).get("marshal_ms"),
        "bridge_p50_score_ms": (bridge_decomp or {}).get("score_ms"),
        "bridge_rows_per_s": bridge_rows_s,
        "inference_images_per_s_per_chip": infer_ips,
        "inference_compute_images_per_s_per_chip": infer_compute_ips,
        "pipeline_rows_per_s": pipe_rows_s,
        "pipeline_rows_per_s_unfused": pipe_rows_s_unfused,
        "pipeline_crossings": pipe_crossings,
        "train_prefetch_images_per_s_per_chip": (train_ab or {}).get(
            "prefetch", {}).get("images_per_s_per_chip"),
        "train_sync_images_per_s_per_chip": (train_ab or {}).get(
            "sync", {}).get("images_per_s_per_chip"),
        "train_input_bound_fraction": (train_ab or {}).get(
            "prefetch", {}).get("input_bound_fraction"),
        "train_input_ab": train_ab,
        "train_preprocess_images_per_s_per_chip": (train_pp_ab or {}).get(
            "device_thin", {}).get("images_per_s_per_chip"),
        "train_preprocess_host_images_per_s_per_chip": (
            train_pp_ab or {}).get("host_f32", {}).get(
            "images_per_s_per_chip"),
        "train_preprocess_h2d_reduction": (train_pp_ab or {}).get(
            "h2d_reduction"),
        "train_preprocess_input_bound_fraction": (train_pp_ab or {}).get(
            "device_thin", {}).get("input_bound_fraction"),
        "train_preprocess_ab": train_pp_ab,
        "serve_rows_per_s": (serve_ab or {}).get(
            "dynamic_c8", {}).get("rows_per_s"),
        "serve_p99_ms": (serve_ab or {}).get(
            "dynamic_c8", {}).get("p99_ms"),
        "serve_ab": serve_ab,
        "serve_sharded": serve_sharded,
        "serve_sharded_speedup": (serve_sharded or {}).get("speedup"),
        "serve_swap": serve_swap,
        "serve_swap_p99_ms_steady": (serve_swap or {}).get(
            "steady", {}).get("p99_ms"),
        "serve_swap_p99_ms_during": (serve_swap or {}).get(
            "swap", {}).get("p99_ms"),
        "serve_swap_dropped": (serve_swap or {}).get(
            "swap", {}).get("dropped"),
        "serve_generate": serve_generate,
        "serve_generate_tokens_per_s": (serve_generate or {}).get(
            "tokens_per_s"),
        "serve_generate_ttft_p50_ms": (serve_generate or {}).get(
            "ttft_p50_ms"),
        "serve_generate_ttft_p99_ms": (serve_generate or {}).get(
            "ttft_p99_ms"),
        "serve_generate_itl_p99_ms": (serve_generate or {}).get(
            "itl_p99_ms"),
        "serve_generate_slot_occupancy": (serve_generate or {}).get(
            "slot_occupancy_mean"),
        "serve_load_wall_cold_s": (serve_load_wall or {}).get(
            "cold", {}).get("load_wall_s"),
        "serve_load_wall_warm_s": (serve_load_wall or {}).get(
            "warm", {}).get("load_wall_s"),
        "serve_load_wall": serve_load_wall,
        "serve_fleet": serve_fleet,
        "serve_fleet_rows_per_s_1b": (serve_fleet or {}).get(
            "fleet1", {}).get("rows_per_s"),
        "serve_fleet_rows_per_s_2b": (serve_fleet or {}).get(
            "fleet2", {}).get("rows_per_s"),
        "serve_fleet_speedup": (serve_fleet or {}).get("speedup"),
        "serve_fleet_kill_p99_ms": (serve_fleet or {}).get(
            "kill", {}).get("p99_ms"),
        "serve_fleet_kill_errors": (serve_fleet or {}).get(
            "kill", {}).get("errors"),
        "deploy": deploy,
        "deploy_wall_cold_s": (deploy or {}).get(
            "cold", {}).get("deploy_wall_s"),
        "deploy_wall_warm_s": (deploy or {}).get(
            "warm", {}).get("deploy_wall_s"),
        "serve_precision_ab": serve_precision,
        **{f"serve_rows_per_s_{p}": (serve_precision or {}).get(
            p, {}).get("serve_rows_per_s") for p in ("f32", "bf16",
                                                     "int8w")},
        **{f"serve_p99_ms_{p}": (serve_precision or {}).get(
            p, {}).get("serve_p99_ms") for p in ("f32", "bf16",
                                                 "int8w")},
        **{f"serve_parity_max_abs_{p}": (serve_precision or {}).get(
            p, {}).get("parity_max_abs") for p in ("bf16", "int8w")},
        "tunnel_upload_mb_s": tunnel_mb_s,
        "mxu_matmul_tf_s": mxu_tf_s,
        "fetch_rtt_ms": rtt_ms,
        "obs_snapshot_path": obs_archive,
        "obs_counters": (obs_snapshot["counters"]
                         if obs_snapshot else None),
        **extra,
    }

    # --check: the perf-regression sentinel (tools/bench_check.py) runs
    # over this line vs the archived BENCH_r*.json trajectory AFTER the
    # obs archiving above, and its verdict rides IN the JSON line so the
    # trajectory itself records whether each round was regression-free
    rc = 0
    import sys
    if "--check" in sys.argv:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import bench_check
        repo = os.path.dirname(os.path.abspath(__file__))
        report = bench_check.check_line(line,
                                        bench_check.load_rounds(repo))
        line["bench_check_verdict"] = report["verdict"]
        line["bench_check_regressions"] = [
            r["key"] for r in report["regressions"]]
        if report["verdict"] == "regressed":
            rc = 2
            print(bench_check.format_report(report), file=sys.stderr)

    print(json.dumps(line))
    return rc


def _main_guarded() -> None:
    """The driver contract is ONE JSON line on stdout, always — a device
    or tunnel failure mid-bench must degrade to an error-labeled record,
    not an empty capture."""
    try:
        rc = main()
    except BaseException as e:  # noqa: BLE001 — last-resort driver record
        print(json.dumps({
            "metric": METRIC_NAME,
            "value": None, "unit": METRIC_UNIT, "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}",
        }))
        raise
    if rc:
        raise SystemExit(rc)


if __name__ == "__main__":
    _main_guarded()
